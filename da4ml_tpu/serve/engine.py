"""The serve engine: model registry, batcher threads, and the robustness
envelope around ``runtime.jax_backend`` executors.

One :class:`ServeEngine` owns a multi-model registry. Per model it runs a
bounded :class:`~.batching.AdmissionQueue` and one batcher thread that
coalesces requests into canonical-grid batches (docs/serving.md). The
envelope, built from the ``reliability`` primitives:

- **deadlines** — expired requests are rejected *before* dispatch;
- **circuit breaker** per model (``serve.<model>`` in the shared breaker
  registry, so ``/healthz`` and the OpenMetrics ``breaker.state`` family
  see it like any backend breaker);
- **degradation ladder** — a dispatch failure falls back to the bit-exact
  ``reliability.run_program`` chain for *that batch*; an OPEN breaker
  drops the serve path to degraded mode: smaller max batch on the
  fallback chain (``degraded='fallback'``) or structured 503s with
  Retry-After (``degraded='shed'``). Answers are never wrong — all chain
  runtimes are bit-exact — only slower or shed;
- **hedged dispatch** — an optional straggler hedge races the fallback
  chain against a slow device batch and takes the first finisher;
- **graceful drain / hot reload** — drain serves every accepted request
  then stops; reload builds + warms the new executor off to the side and
  swaps it atomically between batches, dropping nothing.

The compiled-executor cache is LRU-bounded across models; ``warmup``
pre-dispatches every canonical batch rung so a warm server never meets a
new XLA shape (the ``serve.shape_miss`` counter stays 0).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np
from numpy.typing import NDArray

from .. import telemetry
from ..ir.dais_binary import decode
from ..parallel.shapes import canon_dim, grid_rungs
from ..reliability.breaker import breaker_for
from ..reliability.errors import InvalidInputError
from ..reliability.faults import fault_check
from ..reliability.locktrace import make_lock
from .batching import (
    AdmissionQueue,
    DeadlineExpired,
    Draining,
    InferRequest,
    ModelNotFound,
    ModelUnavailable,
    ServeRejected,
)

#: batch fill-ratio histogram ladder (rows dispatched / row budget)
FILL_BUCKETS: tuple[float, ...] = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)

#: queue age beyond which /healthz reports the serve plane degraded
DEFAULT_QUEUE_STALL_S = 10.0


def _queue_stall_s() -> float:
    try:
        return float(os.environ.get('DA4ML_SERVE_STALL_S', '') or DEFAULT_QUEUE_STALL_S)
    except ValueError:
        return DEFAULT_QUEUE_STALL_S


@dataclass
class ServeConfig:
    """Tuning knobs of the serve plane (docs/serving.md#tuning)."""

    max_batch_rows: int = 256  #: row budget per coalesced device batch
    max_latency_ms: float = 5.0  #: coalescing window from the first queued request
    queue_cap_rows: int = 1024  #: hard admission ceiling (rows) per model
    shed_policy: str = 'reject-newest'  #: or 'deadline-edf'
    default_deadline_ms: float | None = 1000.0  #: applied when a request carries none (None = unbounded)
    hedge_ms: float = 0.0  #: straggler hedge: race the fallback chain after this long (0 = off)
    degraded: str = 'fallback'  #: OPEN-breaker mode: 'fallback' (small batches, bit-exact chain) or 'shed' (503)
    degraded_max_rows: int = 32  #: row budget while degraded
    breaker_threshold: int = 3  #: consecutive dispatch failures that open the model's breaker
    breaker_reset_s: float = 5.0  #: OPEN cooldown before a half-open probe
    executor_cache_cap: int = 8  #: compiled executors kept across models (LRU)
    prewarm: bool = True  #: warm the canonical batch grid on load
    fallback_chain: tuple[str, ...] = ('cpp', 'numpy')  #: bit-exact chain for degraded/hedged batches

    def __post_init__(self):
        if self.shed_policy not in ('reject-newest', 'deadline-edf'):
            raise ValueError(f'bad shed_policy {self.shed_policy!r}')
        if self.degraded not in ('fallback', 'shed'):
            raise ValueError(f"degraded must be 'fallback' or 'shed', got {self.degraded!r}")


@dataclass
class _ModelState:
    name: str
    binaries: list[NDArray[np.int32]]
    source: str | None
    partition: object = None  # artifact PartitionPlan (model-axis cut) or None
    version: int = 1
    queue: AdmissionQueue = field(default=None)  # type: ignore[assignment]
    lock: threading.Lock = field(default_factory=lambda: make_lock('serve.engine.model'))
    stop: threading.Event = field(default_factory=threading.Event)
    warm_rows: set[int] = field(default_factory=set)
    n_in: int = 0
    n_out: int = 0
    requests_total: int = 0
    deadline_miss_total: int = 0
    degraded_total: int = 0
    served_rows_total: int = 0
    served_s_total: float = 0.0


def _as_binaries(source) -> tuple[list[NDArray[np.int32]], str | None, object]:
    """Normalize a model source into ``(binaries, source_path, partition)``.

    Accepts a saved CombLogic/Pipeline ``.json`` path, an export artifact
    directory (``da4ml-tpu export``, digest-checked on load), a live
    ``CombLogic``/``Pipeline``, or raw binaries (one int32 array or a
    list of them). ``partition`` is the artifact's model-axis
    :class:`~..ir.partition.PartitionPlan` when one is stamped into it
    (docs/runtime.md#model-parallel-execution), else None.
    """
    from ..ir.comb import CombLogic, Pipeline

    if isinstance(source, (str, Path)):
        path = Path(source)
        if path.is_dir():
            from .export import load_artifact, load_partition_plan

            binary, meta = load_artifact(path)  # raises ValueError on digest mismatch
            return [binary], str(path), load_partition_plan(path, meta)
        import json

        data = json.loads(path.read_text())
        obj = Pipeline.from_dict(data) if 'stages' in data else CombLogic.from_dict(data)
        bins, _, _ = _as_binaries(obj)
        return bins, str(path), None
    if isinstance(source, Pipeline):
        return [s.to_binary() for s in source.stages], None, None
    if isinstance(source, CombLogic):
        return [source.to_binary()], None, None
    if isinstance(source, np.ndarray):
        return [np.asarray(source, dtype=np.int32)], None, None
    if isinstance(source, (list, tuple)):
        return [np.asarray(b, dtype=np.int32) for b in source], None, None
    raise TypeError(f'cannot load a serve model from {type(source).__name__}')


def _same_plan(a, b) -> bool:
    """True when two partition plans (or None) describe the same cut."""
    if a is None or b is None:
        return a is b
    from ..ir.partition import plan_to_dict

    return plan_to_dict(a) == plan_to_dict(b)


#: live engines, for the /healthz–/statusz serve-plane checks
#: (telemetry.obs.health resolves this module via sys.modules — a scrape
#: never imports the serve stack)
_ENGINES: 'weakref.WeakSet[ServeEngine]' = weakref.WeakSet()


class ServeEngine:
    def __init__(self, config: ServeConfig | None = None):
        self.config = config or ServeConfig()
        self._models: dict[str, _ModelState] = {}
        self._workers: dict[str, threading.Thread] = {}
        self._executors: 'dict[str, tuple[int, object]]' = {}  # name -> (version, executor), LRU
        self._exec_lock = make_lock('serve.engine.executors')
        self._lock = make_lock('serve.engine.registry')
        self._stop = threading.Event()
        self._draining = False
        self._shed_times: list[float] = []  # recent shed timestamps (rate window)
        self.started_at = time.time()
        _ENGINES.add(self)

    # -- registry ------------------------------------------------------------

    def load_model(self, name: str, source, prewarm: bool | None = None) -> None:
        """Load (or replace) a model and start its batcher thread."""
        binaries, src, plan = _as_binaries(source)
        prog0, progL = decode(binaries[0]), decode(binaries[-1])
        with self._lock:
            existing = self._models.get(name)
            if existing is not None:
                raise ValueError(f'model {name!r} already loaded (use reload())')
            state = _ModelState(name=name, binaries=binaries, source=src, partition=plan)
            state.n_in, state.n_out = prog0.n_in, progL.n_out
            state.queue = AdmissionQueue(self.config.queue_cap_rows, self.config.shed_policy)
            self._models[name] = state
            worker = threading.Thread(target=self._batcher_loop, args=(state,), name=f'da4ml-serve-{name}', daemon=True)
            self._workers[name] = worker
        breaker_for(f'serve.{name}', self.config.breaker_threshold, self.config.breaker_reset_s)
        worker.start()
        if self.config.prewarm if prewarm is None else prewarm:
            self.warmup(name)

    def reload(self, name: str, source=None) -> int:
        """Hot-swap a model's executor without dropping queued work.

        Builds (and warms) the replacement off to the side, then swaps the
        binaries + executor atomically between batches; in-flight batches
        finish on the old executor. ``source=None`` re-reads the original
        path. Returns the new version number.
        """
        state = self._state(name)
        if source is None:
            if state.source is None:
                source = state.binaries  # rebuild in place (executor refresh)
            else:
                source = state.source
        binaries, src, plan = _as_binaries(source)
        prog0, progL = decode(binaries[0]), decode(binaries[-1])
        if (prog0.n_in, progL.n_out) != (state.n_in, state.n_out):
            raise ValueError(
                f'reload of {name!r} changes the interface '
                f'({state.n_in}->{prog0.n_in} in, {state.n_out}->{progL.n_out} out); load a new model name instead'
            )
        new_version = state.version + 1
        # same-program reload (e.g. re-pointing at an export artifact of the
        # live model): the warm executor is reused as-is — zero new XLA
        # compiles, the canonical grid stays warm. A changed partition plan
        # changes the compiled program, so it forces a rebuild like changed
        # binaries would.
        executor = None
        same = (
            len(binaries) == len(state.binaries)
            and all(np.array_equal(a, b) for a, b in zip(binaries, state.binaries))
            and _same_plan(plan, state.partition)
        )
        if same:
            with self._exec_lock:
                entry = self._executors.get(name)
                if entry is not None and entry[0] == state.version:
                    executor = entry[1]
        if executor is not None:
            warm = set(state.warm_rows)
        else:
            executor = self._build_executor(binaries, plan)
            warm = set()
            if self.config.prewarm:
                warm = self._warm_executor(executor, state.n_in)
        with state.lock:
            state.binaries = binaries
            state.version = new_version
            state.warm_rows = warm
            state.partition = plan
            if src is not None:
                state.source = src
        with self._exec_lock:
            self._executors.pop(name, None)
            self._executors[name] = (new_version, executor)  # re-insert = LRU touch
        telemetry.counter('serve.reloads').inc()
        telemetry.instant('serve.reload', model=name, version=new_version)
        return new_version

    def unload(self, name: str) -> None:
        """Drain one model's queue (serving what was accepted) and drop it."""
        state = self._state(name)
        deadline = time.monotonic() + 30.0
        while state.queue.depth_requests() and time.monotonic() < deadline:
            time.sleep(0.01)
        state.stop.set()
        with self._lock:
            self._models.pop(name, None)
            worker = self._workers.pop(name, None)
        with self._exec_lock:
            self._executors.pop(name, None)
        if worker is not None:
            worker.join(max(deadline - time.monotonic(), 0.05))

    def models(self) -> dict:
        """The ``/v1/models`` document."""
        with self._lock:
            states = list(self._models.values())
        with self._exec_lock:
            cached = {n: v for n, (v, _) in self._executors.items()}
        return {
            'models': [
                {
                    'name': s.name,
                    'version': s.version,
                    'source': s.source,
                    'n_in': s.n_in,
                    'n_out': s.n_out,
                    'stages': len(s.binaries),
                    'queue_rows': s.queue.depth_rows(),
                    'queue_requests': s.queue.depth_requests(),
                    'queue_age_s': round(s.queue.oldest_age_s(), 4),
                    'breaker': breaker_for(f'serve.{s.name}').state,
                    'executor_cached': s.name in cached,
                    'warm_rungs': sorted(s.warm_rows),
                    'requests_total': s.requests_total,
                    'shed_total': s.queue.shed_total,
                    'deadline_miss_total': s.deadline_miss_total,
                    'degraded_total': s.degraded_total,
                }
                for s in states
            ],
            'executor_cache': {'occupancy': len(cached), 'cap': self.config.executor_cache_cap, 'entries': cached},
            'draining': self._draining,
        }

    def _state(self, name: str) -> _ModelState:
        with self._lock:
            state = self._models.get(name)
        if state is None:
            raise ModelNotFound(name, list(self._models))
        return state

    # -- executors ------------------------------------------------------------

    def _build_executor(self, binaries: list[NDArray[np.int32]], plan=None):
        from ..runtime.jax_backend import DaisExecutor, PipelineExecutor

        if len(binaries) == 1:
            # the artifact's export-time partition plan (if any) rides along;
            # hosts that cannot host the model mesh ignore it inside the
            # executor (docs/runtime.md#model-parallel-execution)
            return DaisExecutor(decode(binaries[0]), partition_plan=plan)
        return PipelineExecutor([decode(b) for b in binaries])

    def _executor_for(self, state: _ModelState):
        """The model's compiled executor, built on demand into the
        LRU-bounded cross-model cache."""
        with self._exec_lock:
            entry = self._executors.get(state.name)
            if entry is not None and entry[0] == state.version:
                self._executors[state.name] = self._executors.pop(state.name)  # LRU touch (dict keeps insertion order)
                return entry[1]
        executor = self._build_executor(state.binaries, state.partition)
        with self._exec_lock:
            while len(self._executors) >= self.config.executor_cache_cap:
                oldest = next(iter(self._executors))
                if oldest == state.name:
                    self._executors.pop(oldest)
                    continue
                self._executors.pop(oldest)
                telemetry.counter('serve.executor_evictions').inc()
            self._executors[state.name] = (state.version, executor)
        return executor

    def _warm_executor(self, executor, n_in: int) -> set[int]:
        """Dispatch one zero batch per canonical grid rung so every batch
        shape a warm server can produce is already compiled."""
        warm: set[int] = set()
        with telemetry.span('serve.warmup', rungs=0) as sp:
            for r in grid_rungs(self.config.max_batch_rows):
                executor(np.zeros((r, max(n_in, 1)), dtype=np.float64))
                warm.add(r)
            sp.set(rungs=len(warm))
        return warm

    def warmup(self, name: str | None = None) -> int:
        """Synchronously prewarm one model (or all). Returns rung count."""
        names = [name] if name is not None else list(self._models)
        total = 0
        for n in names:
            state = self._state(n)
            executor = self._executor_for(state)
            warm = self._warm_executor(executor, state.n_in)
            with state.lock:
                state.warm_rows = warm
            total += len(warm)
        return total

    # -- request path ---------------------------------------------------------

    def submit(self, name: str, data, deadline_s: float | None = None) -> InferRequest:
        """Validate + admit one request; returns its future-like handle.

        Raises the structured taxonomy on rejection: ModelNotFound,
        InvalidInputError (client bug), QueueFull (shed, with Retry-After),
        Draining.
        """
        from ..runtime.jax_backend import validate_batch

        state = self._state(name)
        if self._draining or self._stop.is_set():
            raise Draining('server is draining; retry against another replica', retry_after_s=1.0)
        x = validate_batch(data, state.n_in, what=f'serve.{name}')
        if x.shape[0] > self.config.max_batch_rows:
            raise InvalidInputError(
                f'serve.{name}: request of {x.shape[0]} rows exceeds the {self.config.max_batch_rows}-row '
                f'batch budget; split the batch client-side'
            )
        if deadline_s is None and self.config.default_deadline_ms is not None:
            deadline_s = self.config.default_deadline_ms / 1e3
        req = InferRequest(x, deadline_s)
        tb = telemetry.current_trace()
        if tb is not None:
            # adopt the submitting thread's trace context: the batcher
            # thread emits this request's waterfall under it
            req.trace_id = tb[0]
            cur = telemetry.current_span()
            req.parent_span_id = cur.span_id if cur is not None else tb[1]
        try:
            state.queue.push(req, rate_rows_s=self._service_rate(state))
        except ServeRejected:
            self._note_shed()
            raise
        state.requests_total += 1
        telemetry.counter('serve.requests').inc()
        telemetry.gauge('serve.queue_depth').set(state.queue.depth_rows())
        return req

    def infer(self, name: str, data, deadline_s: float | None = None) -> NDArray[np.float64]:
        """Blocking submit + wait (the in-process client used by bench and
        the load generator; HTTP handlers do the same)."""
        req = self.submit(name, data, deadline_s)
        timeout = None
        if req.deadline is not None:
            # the batch holding this request may already be mid-dispatch
            # when the deadline fires: give resolution a generous margin
            # (expired-in-queue requests get DeadlineExpired either way)
            timeout = max(req.deadline - time.monotonic(), 0.0) + 30.0
        return req.result(timeout)

    def _service_rate(self, state: _ModelState) -> float | None:
        if state.served_s_total <= 0:
            return None
        return state.served_rows_total / state.served_s_total

    def _note_shed(self) -> None:
        telemetry.counter('serve.shed').inc()
        now = time.monotonic()
        self._shed_times.append(now)
        if len(self._shed_times) > 4096:
            del self._shed_times[:2048]

    def shed_rate_1m(self) -> float:
        now = time.monotonic()
        return sum(1 for t in self._shed_times if now - t < 60.0) / 60.0

    # -- dispatch -------------------------------------------------------------

    def _run_fallback_chain(self, state: _ModelState, x: NDArray[np.float64]) -> NDArray[np.float64]:
        """Bit-exact answer off the device path: the existing
        ``reliability.run_program`` chain, stage by stage, in
        degraded-sized chunks."""
        from ..reliability.orchestrator import run_program

        chunk = max(int(self.config.degraded_max_rows), 1)
        outs = []
        for i in range(0, len(x), chunk):
            part = x[i : i + chunk]
            for b in state.binaries:
                part = run_program(b, part, chain=self.config.fallback_chain)
            outs.append(part)
        return np.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]

    def _device_call(self, state: _ModelState, x: NDArray[np.float64]) -> NDArray[np.float64]:
        """One padded, canonical-shape executor call (the breaker-guarded
        primary path); ``serve.dispatch`` is a fault-injection site for the
        chaos drill."""
        fault_check('serve.dispatch')
        executor = self._executor_for(state)
        n = len(x)
        target = canon_dim(n, lo=1, even=False)
        if target not in state.warm_rows:
            telemetry.counter('serve.shape_miss').inc()
            state.warm_rows.add(target)
        else:
            telemetry.counter('serve.shape_hit').inc()
        if target != n:
            x = np.pad(x, ((0, target - n), (0, 0)))
        y = executor(x)
        return y[:n]

    def _dispatch(self, state: _ModelState, x: NDArray[np.float64]) -> tuple[NDArray[np.float64], str]:
        """The degradation ladder for one coalesced batch. Returns
        ``(outputs, served_by)``; raises :class:`ModelUnavailable` only
        when configured to shed while the breaker is open."""
        br = breaker_for(f'serve.{state.name}', self.config.breaker_threshold, self.config.breaker_reset_s)
        if br.allow():
            try:
                y = self._hedged_device_call(state, x) if self.config.hedge_ms > 0 else self._device_call(state, x)
            except InvalidInputError:
                br.record_success()  # the request is wrong, not the backend
                raise
            except Exception as e:
                br.record_failure()
                telemetry.counter('serve.dispatch_failures').inc()
                telemetry.instant('serve.dispatch_failure', model=state.name, error=type(e).__name__)
                # this batch is already accepted: answer it bit-exactly off
                # the fallback chain rather than shedding accepted work
                state.degraded_total += 1
                telemetry.counter('serve.degraded').inc()
                return self._run_fallback_chain(state, x), 'fallback'
            else:
                if isinstance(y, tuple):  # hedge returns (result, served_by)
                    if y[1] == 'jax':
                        br.record_success()
                    return y
                br.record_success()
                return y, 'jax'
        # breaker OPEN: degraded mode
        if self.config.degraded == 'shed':
            remaining = max(self.config.breaker_reset_s, 0.1)
            raise ModelUnavailable(
                f'model {state.name!r}: serve breaker open; shedding while degraded', retry_after_s=remaining
            )
        state.degraded_total += 1
        telemetry.counter('serve.degraded').inc()
        return self._run_fallback_chain(state, x), 'fallback'

    def _hedged_device_call(self, state: _ModelState, x: NDArray[np.float64]):
        """Race the device batch against the fallback chain after
        ``hedge_ms`` of silence; first bit-exact answer wins."""
        box: dict = {}
        done = threading.Event()

        def primary():
            try:
                box['y'] = self._device_call(state, x)
            except BaseException as e:  # noqa: BLE001 - relayed below
                box['e'] = e
            done.set()

        t = threading.Thread(target=primary, name=f'da4ml-serve-hedge-{state.name}', daemon=True)
        t.start()
        if done.wait(self.config.hedge_ms / 1e3):
            if 'e' in box:
                raise box['e']
            return box['y'], 'jax'
        telemetry.counter('serve.hedge_fired').inc()
        y2 = self._run_fallback_chain(state, x)
        if done.is_set() and 'y' in box:
            return box['y'], 'jax'
        telemetry.counter('serve.hedge_won').inc()
        return y2, 'hedge-fallback'

    # -- batcher loop ---------------------------------------------------------

    def _effective_max_rows(self, state: _ModelState) -> int:
        br = breaker_for(f'serve.{state.name}', self.config.breaker_threshold, self.config.breaker_reset_s)
        if br.state != 'closed':
            return min(self.config.max_batch_rows, self.config.degraded_max_rows)
        return self.config.max_batch_rows

    def _batcher_loop(self, state: _ModelState) -> None:
        window_s = self.config.max_latency_ms / 1e3
        while True:
            batch = state.queue.take_batch(self._effective_max_rows(state), window_s, state.stop)
            if not batch:
                if state.stop.is_set():
                    return
                continue
            self._serve_batch(state, batch)

    def _serve_batch(self, state: _ModelState, batch: list[InferRequest]) -> None:
        now = time.monotonic()
        live: list[InferRequest] = []
        for r in batch:
            if r.expired(now):
                state.deadline_miss_total += 1
                telemetry.counter('serve.deadline_miss').inc()
                r.set_error(
                    DeadlineExpired(f'request {r.id}: deadline passed while queued ({r.wait_s() * 1e3:.1f} ms)')
                )
            else:
                live.append(r)
        if not live:
            return
        rows = sum(r.n_rows for r in live)
        x = np.concatenate([r.x for r in live], axis=0) if len(live) > 1 else live[0].x
        t0 = time.perf_counter()
        t_exec0 = time.monotonic()
        with telemetry.span('serve.batch', model=state.name, rows=rows, requests=len(live)) as sp:
            try:
                y, served_by = self._dispatch(state, x)
            except ServeRejected as e:
                for r in live:
                    r.set_error(e)
                sp.set(outcome=type(e).__name__)
                return
            except Exception as e:  # the fallback chain itself failed
                err = ModelUnavailable(f'model {state.name!r}: all serve paths failed: {e}', retry_after_s=1.0)
                for r in live:
                    r.set_error(err)
                sp.set(outcome='error')
                return
            sp.set(outcome=served_by)
        dt = time.perf_counter() - t0
        t_exec1 = time.monotonic()
        trace_on = telemetry.tracing_active()
        waterfall_on = trace_on or telemetry.metrics_on()
        off = 0
        for r in live:
            r.t_exec0 = t_exec0
            r.t_exec1 = t_exec1
            r.set_result(y[off : off + r.n_rows], served_by)
            off += r.n_rows
            telemetry.histogram('serve.latency_s').observe(r.wait_s(), trace_id=r.trace_id)
            telemetry.histogram('serve.queue_wait_s').observe(max(r.wait_s() - dt, 0.0))
            if waterfall_on:
                segs = r.segments()
                for seg in ('queue', 'coalesce', 'execute', 'serialize'):
                    if seg in segs:
                        telemetry.histogram(f'request.{seg}_s').observe(segs[seg], trace_id=r.trace_id)
                if trace_on and r.trace_id is not None:
                    self._emit_request_waterfall(r)
        state.served_rows_total += rows
        state.served_s_total += dt
        telemetry.counter('serve.batches').inc()
        telemetry.counter('serve.samples').inc(rows)
        telemetry.histogram('serve.batch_rows', telemetry.COUNT_BUCKETS).observe(rows)
        telemetry.histogram('serve.batch_fill', FILL_BUCKETS).observe(rows / max(self.config.max_batch_rows, 1))
        telemetry.gauge('serve.queue_depth').set(state.queue.depth_rows())
        telemetry.gauge('serve.queue_age_s').set(state.queue.oldest_age_s())

    def _emit_request_waterfall(self, r: InferRequest) -> None:
        """Emit the request's queue/coalesce/dispatch/execute/serialize
        segments as trace spans under its adopted trace context. The
        brackets were stamped on the batcher thread while the request's own
        handler thread blocks in ``result()``, so they go through
        :func:`telemetry.emit_span` with explicit timing/parentage instead
        of the thread-stack span API."""
        from ..telemetry.core import monotonic_ts_us

        brackets = (
            ('request.queue', r.t_enq, r.t_deq),
            ('request.coalesce', max(r.t_open, r.t_enq) if r.t_open is not None else None, r.t_deq),
            ('request.dispatch', r.t_deq, r.t_exec0),
            ('request.execute', r.t_exec0, r.t_exec1),
            ('request.serialize', r.t_exec1, r.t_done),
        )
        for name, a, b in brackets:
            if a is None or b is None:
                continue
            telemetry.emit_span(
                name,
                monotonic_ts_us(a),
                max(b - a, 0.0),
                trace_id=r.trace_id,
                parent_id=r.parent_span_id,
                req=r.id,
                rows=r.n_rows,
                batch_rows=r.batch_rows,
            )

    # -- lifecycle ------------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting, serve everything already accepted, stop batchers.

        Returns True when every queue drained and every batcher exited
        within ``timeout`` — the zero-lost-accepted-requests guarantee of
        SIGTERM shutdown (tests/test_serve.py).
        """
        self._draining = True
        deadline = time.monotonic() + timeout
        with self._lock:
            states = list(self._models.values())
        for s in states:
            while s.queue.depth_requests() and time.monotonic() < deadline:
                time.sleep(0.005)
        self._stop.set()
        for s in states:
            s.stop.set()
        ok = all(s.queue.depth_requests() == 0 for s in states)
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            w.join(max(deadline - time.monotonic(), 0.05))
            ok = ok and not w.is_alive()
        return ok

    def close(self, timeout: float = 30.0) -> bool:
        ok = self.drain(timeout)
        _ENGINES.discard(self)
        return ok

    # -- health ---------------------------------------------------------------

    def health_doc(self) -> dict:
        """Serve-plane health: queue stall, shed rate, per-model breakers
        (feeds the process /healthz — telemetry.obs.health)."""
        stall_s = _queue_stall_s()
        with self._lock:
            states = list(self._models.values())
        models = {}
        degraded = False
        for s in states:
            br_state = breaker_for(f'serve.{s.name}').state
            age = s.queue.oldest_age_s()
            stalled = age > stall_s
            degraded = degraded or stalled or br_state == 'open'
            models[s.name] = {
                'queue_rows': s.queue.depth_rows(),
                'queue_age_s': round(age, 4),
                'stalled': stalled,
                'breaker': br_state,
                'shed_total': s.queue.shed_total,
                'deadline_miss_total': s.deadline_miss_total,
                'degraded_total': s.degraded_total,
            }
        # draining wins over degraded: a draining server is about to exit,
        # so routers must stop sending regardless of anything else — the
        # explicit state is what lets them stop BEFORE the replica vanishes
        status = 'draining' if self._draining else ('degraded' if degraded else 'ok')
        return {
            'status': status,
            'draining': self._draining,
            'shed_rate_1m': round(self.shed_rate_1m(), 4),
            'queue_stall_threshold_s': stall_s,
            'models': models,
        }


def serve_health() -> dict | None:
    """Aggregate health over live engines (None when none exist) — resolved
    by ``telemetry.obs.health`` via ``sys.modules``, never by import."""
    engines = list(_ENGINES)
    if not engines:
        return None
    docs = [e.health_doc() for e in engines]
    if any(d['status'] == 'draining' for d in docs):
        status = 'draining'
    elif any(d['status'] == 'degraded' for d in docs):
        status = 'degraded'
    else:
        status = 'ok'
    merged_models: dict = {}
    for d in docs:
        merged_models.update(d['models'])
    return {
        'status': status,
        'engines': len(docs),
        'draining': any(d['draining'] for d in docs),
        'shed_rate_1m': round(sum(d['shed_rate_1m'] for d in docs), 4),
        'models': merged_models,
    }


def serve_status() -> dict | None:
    """Loaded models + executor-cache occupancy for ``/statusz``."""
    engines = list(_ENGINES)
    if not engines:
        return None
    out = {'engines': []}
    for e in engines:
        out['engines'].append(e.models())
    return out
