"""Replica fleet driver: N serve processes, one lease-file registry.

The request plane scales across processes (and hosts) the same way solve
campaigns do (docs/distributed.md): no coordinator, only files on a shared
directory. Each replica holds a short-TTL lease (``reliability.lease``) on
its slot plus a sidecar document with its bound URL; routers
(:mod:`.router`) discover the live set by listing leases — a replica that
dies simply stops renewing and ages out of the registry within
``ttl + grace`` seconds, no deregistration RPC required.

Registry layout (one fleet = one directory)::

    <registry>/leases/replica-<id>.lease   liveness claims (reliability.lease)
    <registry>/<id>.replica.json           sidecar: url, pid, host, artifact

The slot lease doubles as the restart gate: a replacement replica claims
``replica-<id>`` through the same single-winner steal machinery campaign
workers use, so a SIGKILLed replica's slot is adopted by exactly one
successor even when restarts race (tests/test_fleet.py).

:class:`Fleet` is the local driver behind ``da4ml-tpu fleet``: it spawns N
``da4ml-tpu serve`` subprocesses hot-loading the same PR-14 export
artifact, supervises them (restart with exponential backoff on crash),
and points them all at one shared solution store with per-replica local
cache tiers (``DA4ML_STORE_LOCAL_TIER``, :mod:`..store.tiered`) so a
restarted replica warms from the shared tier instead of re-solving.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import weakref
from pathlib import Path

from .. import telemetry
from ..reliability.checkpoint import atomic_write_bytes
from ..reliability.lease import DEFAULT_GRACE_S, claim_lease, default_owner, list_leases, release_lease, renew_lease
from ..reliability.locktrace import make_lock

#: replica liveness lease TTL: short enough that routers drop a SIGKILLed
#: replica within seconds, long enough that renew-at-ttl/3 is cheap
DEFAULT_REPLICA_TTL_S = 5.0

#: restart backoff bounds (exponential, per slot)
RESTART_BACKOFF_S = 0.5
RESTART_BACKOFF_CAP_S = 5.0

_LEASE_PREFIX = 'replica-'


# ------------------------------------------------------------------ registry


class ReplicaAnnouncement:
    """One replica's presence in the registry: the slot lease (renewed at
    ttl/3 by a daemon thread) plus the URL sidecar. ``close()`` withdraws
    both — routers stop routing here within one discovery cycle."""

    def __init__(self, registry_dir: str | os.PathLike, replica_id: str, lease, doc: dict):
        self.registry_dir = Path(registry_dir)
        self.replica_id = replica_id
        self.lease = lease
        self.doc = doc
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._renew_loop, name=f'da4ml-replica-renew-{replica_id}', daemon=True
        )
        self._thread.start()

    def _renew_loop(self) -> None:
        interval = max(self.lease.ttl_s / 3.0, 0.2)
        while not self._stop.wait(interval):
            try:
                if not renew_lease(self.lease):
                    # slot stolen (we were presumed dead): stop announcing —
                    # exactly one replica may own a slot at a time
                    telemetry.counter('fleet.announcements_lost').inc()
                    return
            except OSError:
                return

    @property
    def live(self) -> bool:
        return self._thread.is_alive() and not self.lease.lost

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        try:
            (self.registry_dir / f'{self.replica_id}.replica.json').unlink()
        except OSError:
            pass
        try:
            release_lease(self.lease)
        except OSError:
            pass


def announce_replica(
    registry_dir: str | os.PathLike,
    replica_id: str,
    url: str,
    meta: dict | None = None,
    ttl_s: float = DEFAULT_REPLICA_TTL_S,
) -> ReplicaAnnouncement | None:
    """Claim the ``replica-<id>`` slot and publish the URL sidecar; None
    when another *live* process holds the slot (an expired holder is stolen
    through the lease machinery — single winner)."""
    registry = Path(registry_dir)
    registry.mkdir(parents=True, exist_ok=True)
    # per-announcement owner token: the default host:pid owner would let a
    # second announcement in the same process silently adopt the first's
    # live lease instead of being refused (slots are exclusive)
    owner = f'{default_owner()}:{os.urandom(4).hex()}'
    lease = claim_lease(registry / 'leases', f'{_LEASE_PREFIX}{replica_id}', owner=owner, ttl_s=ttl_s)
    if lease is None:
        return None
    doc = {
        'replica_id': replica_id,
        'url': url,
        'pid': os.getpid(),
        'host': socket.gethostname(),
        'announced_at': round(time.time(), 3),
        **(meta or {}),
    }
    try:
        atomic_write_bytes(registry / f'{replica_id}.replica.json', json.dumps(doc, sort_keys=True).encode())
    except OSError:
        release_lease(lease)
        return None
    telemetry.counter('fleet.announcements').inc()
    return ReplicaAnnouncement(registry, replica_id, lease, doc)


def discover_replicas(registry_dir: str | os.PathLike, grace_s: float = DEFAULT_GRACE_S) -> list[dict]:
    """The live replica set: every unexpired ``replica-*`` lease with a
    readable sidecar, sorted by id. Safe to call from any process — it only
    reads. A replica whose lease expired (it died, or is stalled past
    renewal) is excluded even if its sidecar file remains."""
    registry = Path(registry_dir)
    now = time.time()
    out: list[dict] = []
    for key, lease_doc in sorted(list_leases(registry / 'leases').items()):
        if not key.startswith(_LEASE_PREFIX):
            continue
        if now > float(lease_doc.get('expires_at', 0.0)) + grace_s:
            continue
        replica_id = key[len(_LEASE_PREFIX) :]
        try:
            doc = json.loads((registry / f'{replica_id}.replica.json').read_text())
        except (OSError, ValueError):
            continue
        doc['lease'] = {
            'owner': lease_doc.get('owner'),
            'expires_at': lease_doc.get('expires_at'),
            'generation': lease_doc.get('generation'),
        }
        out.append(doc)
    return out


# --------------------------------------------------------------------- fleet


class _Slot:
    """One supervised replica slot: its subprocess, restart count, log."""

    __slots__ = ('replica_id', 'proc', 'restarts', 'log_path', 'backoff_s')

    def __init__(self, replica_id: str, log_path: Path):
        self.replica_id = replica_id
        self.proc: subprocess.Popen | None = None
        self.restarts = 0
        self.log_path = log_path
        self.backoff_s = RESTART_BACKOFF_S


_FLEETS: 'weakref.WeakSet[Fleet]' = weakref.WeakSet()


class Fleet:
    """Spawn + supervise N local ``da4ml-tpu serve`` replicas over one
    artifact and one registry directory.

    Every replica gets the same shared solution store
    (``DA4ML_SOLUTION_STORE``) and its own local cache tier
    (``DA4ML_STORE_LOCAL_TIER=<fleet_dir>/local/<id>``), so the first
    replica to solve a key publishes it for the whole fleet and a restarted
    replica warms from the shared tier. A crashed replica is restarted with
    exponential backoff; the restarted process re-claims its slot lease
    through the single-winner steal path."""

    def __init__(
        self,
        artifact: str | os.PathLike,
        replicas: int = 4,
        fleet_dir: str | os.PathLike | None = None,
        model_name: str = 'default',
        shared_store: str | os.PathLike | None = None,
        serve_args: list[str] | None = None,
        env: dict | None = None,
        replica_ttl_s: float = DEFAULT_REPLICA_TTL_S,
        trace_dir: str | os.PathLike | None = None,
    ):
        import tempfile

        self.artifact = Path(artifact)
        self.n = max(1, int(replicas))
        self.model_name = model_name
        self.fleet_dir = Path(fleet_dir) if fleet_dir is not None else Path(tempfile.mkdtemp(prefix='da4ml-fleet-'))
        self.registry_dir = self.fleet_dir / 'registry'
        self.shared_store = Path(shared_store) if shared_store is not None else None
        self.trace_dir = Path(trace_dir) if trace_dir is not None else None
        if self.trace_dir is not None:
            self.trace_dir.mkdir(parents=True, exist_ok=True)
        self.serve_args = list(serve_args or [])
        self.replica_ttl_s = replica_ttl_s
        self._extra_env = dict(env or {})
        self._stop = threading.Event()
        self._lock = make_lock('serve.fleet.slots')
        (self.fleet_dir / 'logs').mkdir(parents=True, exist_ok=True)
        self.registry_dir.mkdir(parents=True, exist_ok=True)
        self._slots = [_Slot(f'r{i}', self.fleet_dir / 'logs' / f'r{i}.log') for i in range(self.n)]
        self._supervisors: list[threading.Thread] = []
        _FLEETS.add(self)

    # -- spawning ------------------------------------------------------------

    def _env_for(self, slot: _Slot) -> dict:
        env = dict(os.environ)
        env.update(self._extra_env)
        if self.shared_store is not None:
            env['DA4ML_SOLUTION_STORE'] = str(self.shared_store)
            local = self.fleet_dir / 'local' / slot.replica_id
            local.mkdir(parents=True, exist_ok=True)
            env['DA4ML_STORE_LOCAL_TIER'] = str(local)
        if self.trace_dir is not None:
            # one JSONL trace per replica *incarnation* (sinks truncate on
            # open): a restarted replica writes a fresh file instead of
            # clobbering its predecessor's spans; the collector merges all
            env['DA4ML_TRACE'] = str(self.trace_dir / f'{slot.replica_id}-{slot.restarts}.jsonl')
        return env

    def _spawn(self, slot: _Slot) -> subprocess.Popen:
        cmd = [
            sys.executable,
            '-m',
            'da4ml_tpu',
            'serve',
            f'{self.model_name}={self.artifact}',
            '--port',
            '0',
            '--registry',
            str(self.registry_dir),
            '--replica-id',
            slot.replica_id,
            *self.serve_args,
        ]
        if self.shared_store is not None and '--solve-store' not in self.serve_args:
            cmd += ['--solve-store', str(self.shared_store)]
        log = open(slot.log_path, 'ab')
        try:
            proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT, env=self._env_for(slot))
        finally:
            log.close()  # the child holds its own fd now
        telemetry.counter('fleet.spawns').inc()
        return proc

    def _supervise(self, slot: _Slot) -> None:
        while not self._stop.is_set():
            proc = slot.proc
            if proc is None:
                return
            rc = proc.wait()
            if self._stop.is_set():
                return
            # crash (or unexpected clean exit): restart with backoff — the
            # fresh process steals the expired slot lease and re-announces
            slot.restarts += 1
            telemetry.counter('fleet.restarts').inc()
            telemetry.instant('fleet.restart', replica=slot.replica_id, rc=rc, restarts=slot.restarts)
            if self._stop.wait(slot.backoff_s):
                return
            slot.backoff_s = min(slot.backoff_s * 2.0, RESTART_BACKOFF_CAP_S)
            with self._lock:
                if self._stop.is_set():
                    return
                slot.proc = self._spawn(slot)

    def start(self) -> None:
        with self._lock:
            for slot in self._slots:
                slot.proc = self._spawn(slot)
        self._supervisors = [
            threading.Thread(target=self._supervise, args=(s,), name=f'da4ml-fleet-sup-{s.replica_id}', daemon=True)
            for s in self._slots
        ]
        for t in self._supervisors:
            t.start()

    def wait_ready(self, timeout_s: float = 60.0, n: int | None = None) -> list[dict]:
        """Block until ``n`` (default: all) replicas are announced in the
        registry; returns the discovered set. Raises TimeoutError with the
        partial set's ids on expiry."""
        want = self.n if n is None else n
        deadline = time.monotonic() + timeout_s
        while True:
            live = discover_replicas(self.registry_dir)
            if len(live) >= want:
                return live
            if time.monotonic() > deadline:
                ids = sorted(d.get('replica_id', '?') for d in live)
                raise TimeoutError(f'only {len(live)}/{want} replicas announced within {timeout_s}s: {ids}')
            time.sleep(0.1)

    # -- chaos hooks ---------------------------------------------------------

    def kill_replica(self, replica_id: str, sig: int = signal.SIGKILL) -> int | None:
        """Deliver ``sig`` to one replica (default SIGKILL — the crash
        drill); returns the pid signalled, or None if the slot has no live
        process. The supervisor restarts it with backoff."""
        for slot in self._slots:
            if slot.replica_id == replica_id and slot.proc is not None and slot.proc.poll() is None:
                pid = slot.proc.pid
                telemetry.counter('fleet.kills').inc()
                os.kill(pid, sig)
                return pid
        return None

    def replica_url(self, replica_id: str) -> str | None:
        for doc in discover_replicas(self.registry_dir):
            if doc.get('replica_id') == replica_id:
                return doc.get('url')
        return None

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        live = discover_replicas(self.registry_dir)
        by_id = {d.get('replica_id'): d for d in live}
        with self._lock:
            slots = [
                {
                    'replica_id': s.replica_id,
                    'pid': None if s.proc is None else s.proc.pid,
                    'alive': s.proc is not None and s.proc.poll() is None,
                    'restarts': s.restarts,
                    'announced': s.replica_id in by_id,
                    'url': (by_id.get(s.replica_id) or {}).get('url'),
                }
                for s in self._slots
            ]
        return {
            'fleet_dir': str(self.fleet_dir),
            'artifact': str(self.artifact),
            'replicas': slots,
            'n_live': sum(1 for s in slots if s['alive']),
            'n_announced': len(live),
            'registry': live,
        }

    def stop(self, grace_s: float = 15.0) -> None:
        """SIGTERM every replica (graceful drain), escalate to SIGKILL for
        stragglers past ``grace_s``."""
        self._stop.set()
        with self._lock:
            procs = [s.proc for s in self._slots if s.proc is not None]
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + grace_s
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                    p.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        for t in self._supervisors:
            t.join(timeout=2.0)


# ------------------------------------------------------------------- health


def fleet_health() -> dict | None:
    """The /healthz ``fleet`` check for a process driving a fleet (None
    otherwise). Resolved via ``sys.modules`` by ``telemetry.obs.health``."""
    fleets = [f for f in _FLEETS if not f._stop.is_set()]
    if not fleets:
        return None
    checks = []
    for f in fleets:
        st = f.status()
        checks.append(
            {
                'fleet_dir': st['fleet_dir'],
                'n_live': st['n_live'],
                'n_announced': st['n_announced'],
                'n_want': f.n,
                'restarts': sum(s['restarts'] for s in st['replicas']),
            }
        )
    degraded = any(c['n_announced'] < c['n_want'] for c in checks)
    return {'status': 'degraded' if degraded else 'ok', 'fleets': checks}


def fleet_status() -> dict | None:
    """The /statusz ``fleet`` panel (full per-replica detail)."""
    fleets = [f for f in _FLEETS if not f._stop.is_set()]
    if not fleets:
        return None
    return {'fleets': [f.status() for f in fleets]}


__all__ = [
    'DEFAULT_REPLICA_TTL_S',
    'Fleet',
    'ReplicaAnnouncement',
    'announce_replica',
    'discover_replicas',
    'fleet_health',
    'fleet_status',
]
