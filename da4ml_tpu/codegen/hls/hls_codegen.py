"""HLS C++ emitter: one straight-line kernel function per CombLogic stage.

Every live SSA op becomes one int64 statement using the ``da::`` helpers
(dais_hls.hh); lookup tables become static const arrays. Pipelines chain
stage functions under ``#pragma HLS dataflow``. The same source compiles
bit-exactly with plain g++ (emulation) and with Vitis HLS (synthesis).

Parity target: reference src/da4ml/codegen/hls/hls_codegen.py (SSA walk to
ap_fixed C++); the integer-code design here replaces vendor fixed-point
types with explicit wrap/shift semantics.
"""

from __future__ import annotations

from ...ir.comb import CombLogic, Pipeline
from ...ir.types import minimal_kif


def _i32(x: int) -> int:
    return ((int(x) & 0xFFFFFFFF) + (1 << 31)) % (1 << 32) - (1 << 31)


class HLSCombEmitter:
    """Emit one HLS kernel function for a CombLogic stage."""

    def __init__(self, comb: CombLogic, name: str, print_latency: bool = False, flavor: str = 'vitis'):
        self.comb = comb
        self.name = name
        self.print_latency = print_latency
        self.flavor = flavor
        self.kifs = [minimal_kif(op.qint) for op in comb.ops]
        self.widths = [k + i + f for k, i, f in self.kifs]
        self.tables: dict[int, str] = {}
        self.table_decls: list[str] = []

    def _table_name(self, t_idx: int, key_op: int) -> str:
        if t_idx in self.tables:
            return self.tables[t_idx]
        assert self.comb.lookup_tables is not None
        table = self.comb.lookup_tables[t_idx]
        tname = f'{self.name}_tbl_{table.spec.hash[:12]}'
        vals = ', '.join(str(int(v)) for v in table.table)
        self.table_decls.append(f'static const int64_t {tname}[{len(table.table)}] = {{{vals}}};')
        self.tables[t_idx] = tname
        return tname

    def _op_stmt(self, n: int) -> str:
        comb, op = self.comb, self.comb.ops[n]
        oc = op.opcode
        k, i, f = self.kifs[n]
        sg, w = int(k), self.widths[n]

        def kw(idx):
            kk, ii, ff = self.kifs[idx]
            return int(kk), self.widths[idx], ff

        if oc == -1:
            expr = f'in[{op.id0}]'
        elif oc in (0, 1):
            _, _, f0 = kw(op.id0)
            _, _, f1 = kw(op.id1)
            s = int(op.data) + f0 - f1
            gshift = max(max(f0, f1 - int(op.data)) - f, 0)
            expr = f'da::shift_add(v{op.id0}, v{op.id1}, {int(oc == 1)}, {s}, {gshift})'
        elif oc in (2, -2):
            _, _, f0 = kw(op.id0)
            v = f'-v{op.id0}' if oc == -2 else f'v{op.id0}'
            expr = f'da::relu_q({v}, {f0}, {sg}, {w}, {f})'
        elif oc in (3, -3):
            _, _, f0 = kw(op.id0)
            v = f'-v{op.id0}' if oc == -3 else f'v{op.id0}'
            expr = f'da::requant({v}, {f0}, {sg}, {w}, {f})'
        elif oc == 4:
            _, _, f0 = kw(op.id0)
            expr = f'da::shl(v{op.id0}, {f - f0}) + INT64_C({int(op.data)})'
        elif oc == 5:
            expr = f'INT64_C({int(op.data)})'
        elif oc in (6, -6):
            ic = int(op.data) & 0xFFFFFFFF
            dhi = _i32(int(op.data) >> 32)
            sc, wc, _ = kw(ic)
            _, _, f0 = kw(op.id0)
            _, _, f1 = kw(op.id1)
            v1 = f'-v{op.id1}' if oc == -6 else f'v{op.id1}'
            r0 = f'da::wrap(da::shl(v{op.id0}, {f - f0}), {sg}, {w})'
            r1 = f'da::wrap(da::shl({v1}, {f - f1 + dhi}), {sg}, {w})'
            expr = f'da::msb(v{ic}, {sc}, {wc}) ? {r0} : {r1}'
        elif oc == 7:
            expr = f'v{op.id0} * v{op.id1}'
        elif oc == 8:
            assert comb.lookup_tables is not None
            tname = self._table_name(int(op.data), op.id0)
            table = comb.lookup_tables[int(op.data)]
            sg0, w0, _ = kw(op.id0)
            zero = -(1 << (w0 - 1)) if sg0 else 0
            pad_left = table.pads(comb.ops[op.id0].qint)[0]
            expr = f'{tname}[v{op.id0} - INT64_C({zero + pad_left})]'
        elif oc in (9, -9):
            sg0, w0, _ = kw(op.id0)
            v = f'-v{op.id0}' if oc == -9 else f'v{op.id0}'
            mask = (1 << w0) - 1
            if op.data == 0:
                expr = f'~({v})' if sg else f'(~({v})) & INT64_C({mask})'
            elif op.data == 1:
                expr = f'int64_t(({v}) != 0)'
            elif op.data == 2:
                expr = f'int64_t((({v}) & INT64_C({mask})) == INT64_C({mask}))'
            else:
                raise ValueError(f'Unknown bit unary data {op.data}')
        elif oc == 10:
            _, _, f0 = kw(op.id0)
            _, _, f1 = kw(op.id1)
            data = int(op.data)
            shift = _i32(data) + f0 - f1
            subop = (data >> 56) & 0xFF
            a = f'-v{op.id0}' if (data >> 32) & 1 else f'v{op.id0}'
            b = f'-v{op.id1}' if (data >> 33) & 1 else f'v{op.id1}'
            if shift > 0:
                b = f'da::shl({b}, {shift})'
            elif shift < 0:
                a = f'da::shl({a}, {-shift})'
            sym = {0: '&', 1: '|', 2: '^'}[subop]
            expr = f'({a}) {sym} ({b})'
        else:
            raise ValueError(f'Unknown opcode {oc} in op {n}')

        lat = f'  // latency={op.latency}' if self.print_latency else ''
        wrap_in_entry = oc == -1  # bridge passes pre-wrapped codes
        del wrap_in_entry
        return f'    const int64_t v{n} = {expr};{lat}'

    def emit_function(self) -> str:
        comb = self.comb
        rc = comb.ref_count
        n_in, n_out = comb.shape
        lines = [f'static void {self.name}(const int64_t in[{max(n_in, 1)}], int64_t out[{max(n_out, 1)}]) {{']
        if self.flavor == 'vitis':
            lines += ['#pragma HLS INLINE off', '#pragma HLS PIPELINE II=1']
        # Intel flavors: II is a component-level property (hls_component_ii on
        # the synthesis top, hls_model._write_synth_files), not a body pragma
        # — Intel's `#pragma ii` binds to the loop that follows it, and these
        # bodies are loop-free straight-line code.
        for n in range(len(comb.ops)):
            if rc[n] == 0:
                continue
            lines.append(self._op_stmt(n))
        for j, (idx, neg) in enumerate(zip(comb.out_idxs, comb.out_negs)):
            if idx < 0:
                lines.append(f'    out[{j}] = 0;')
            else:
                v = f'-v{idx}' if neg else f'v{idx}'
                lines.append(f'    out[{j}] = {v};')
        lines.append('}')
        return '\n'.join(lines)


def emit_hls_kernel(model: CombLogic | Pipeline, name: str, print_latency: bool = False, flavor: str = 'vitis') -> str:
    """Emit the full kernel header: helpers include, tables, stage fns, top fn.

    ``flavor`` selects the synthesis-tool dialect of the wrapping only
    (vitis / hlslib / oneapi, reference hls_model.py:45); the kernel body is
    the same explicit int64 integer code for all three, so g++ emulation and
    bit-exactness are flavor-independent.
    """
    stages = model.stages if isinstance(model, Pipeline) else (model,)
    emitters = [HLSCombEmitter(s, f'{name}_s{si}', print_latency, flavor) for si, s in enumerate(stages)]
    fns = [em.emit_function() for em in emitters]

    n_in = stages[0].shape[0]
    n_out = stages[-1].shape[1]
    lines = [
        f'// Generated by da4ml_tpu: HLS kernel {name}',
        '#pragma once',
        '#include <cstdint>',
        '#include "dais_hls.hh"',
        '',
    ]
    for em in emitters:
        lines.extend(em.table_decls)
    lines.append('')
    lines.extend(fns)
    lines.append('')
    lines.append(f'inline void {name}(const int64_t in[{max(n_in, 1)}], int64_t out[{max(n_out, 1)}]) {{')
    if len(stages) > 1 and flavor == 'vitis':
        lines.append('#pragma HLS dataflow')
    buf = 'in'
    for si, stage in enumerate(stages):
        so = stage.shape[1]
        if si < len(stages) - 1:
            lines.append(f'    int64_t b{si}[{max(so, 1)}];')
            lines.append(f'    {name}_s{si}({buf}, b{si});')
            buf = f'b{si}'
        else:
            lines.append(f'    {name}_s{si}({buf}, out);')
    lines.append('}')
    return '\n'.join(lines) + '\n'
