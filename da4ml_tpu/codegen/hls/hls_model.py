"""HLS project writer: C++ kernel emission, g++-compiled bit-exact emulation,
and a per-flavor synthesis harness (Vitis HLS / Intel HLS / oneAPI).

    <path>/
      src/           {name}.hh kernel + dais_hls.hh helpers + bridge.cc
                     + hls_top.cc (vitis/hlslib) or hls_top_oneapi.cpp
      tcl/           build_vitis.tcl, build_hlslib.sh or build_oneapi.sh
      model/         comb.json / pipeline.json (reloadable IR)
      metadata.json

``compile()`` builds the emulation .so with plain g++ (no vendor headers
needed); ``predict`` is bit-exact against the DAIS interpreter.

Parity target: reference src/da4ml/codegen/hls/hls_model.py.
"""

from __future__ import annotations

import ctypes
import json
import os
import shutil
import subprocess
import uuid
from pathlib import Path

import numpy as np
from numpy.typing import NDArray

from ... import telemetry
from ...ir.comb import CombLogic, Pipeline
from ...ir.types import minimal_kif
from .hls_codegen import emit_hls_kernel

_SRC_DIR = Path(__file__).parent / 'source'


class HLSModel:
    """Write, build and drive one HLS C++ project for a DAIS program.

    ``flavor`` selects the synthesis dialect (reference hls_model.py:45):
    'vitis' (AMD Vitis HLS: HLS pragmas + Vitis TCL), 'hlslib' (Intel HLS
    compiler: ``component`` top, ii pragma, i++ build script) or 'oneapi'
    (Intel oneAPI: SYCL single_task harness, icpx build script). The kernel
    body and the g++ emulation bridge are identical across flavors — the
    explicit int64 integer code replaces the reference's per-flavor
    ap_fixed/ac_fixed type libraries, so bit-exactness is flavor-independent.
    """

    def __init__(
        self,
        solution: CombLogic | Pipeline,
        name: str,
        path: str | Path,
        latency_cutoff: float = -1,
        print_latency: bool = False,
        part: str = 'xcvu13p-flga2577-2-e',
        clock_period: float = 5.0,
        flavor: str = 'vitis',
    ):
        flavor = flavor.lower()
        if flavor not in ('vitis', 'hlslib', 'oneapi'):
            raise ValueError(f'unsupported HLS flavor {flavor!r}; expected vitis, hlslib or oneapi')
        self.flavor = flavor
        if isinstance(solution, CombLogic) and latency_cutoff > 0:
            from ...trace.pipeline import to_pipeline

            solution = to_pipeline(solution, latency_cutoff)
        self.solution = solution
        self.name = name
        self.path = Path(path)
        self.print_latency = print_latency
        self.part = part
        self.clock_period = clock_period
        self._lib: ctypes.CDLL | None = None
        self._lib_path: Path | None = None

    @property
    def is_pipeline(self) -> bool:
        return isinstance(self.solution, Pipeline)

    # ------------------------------------------------------------ layouts

    def _io_consts(self):
        sol = self.solution
        first = sol.stages[0] if self.is_pipeline else sol
        inp_kifs = [minimal_kif(q) for q in sol.inp_qint]
        out_kifs = [minimal_kif(q) for q in sol.out_qint]
        shifts = first.inp_shifts
        in_f_eff = [int(s) + f for s, (_, _, f) in zip(shifts, inp_kifs)]
        in_w = [k + i + f for k, i, f in inp_kifs]
        in_s = [int(k) for k, _, _ in inp_kifs]
        out_f = [f for _, _, f in out_kifs]
        return in_f_eff, in_w, in_s, out_f

    # ------------------------------------------------------------ emission

    def write(self) -> 'HLSModel':
        with telemetry.span('codegen.hls.write', name=self.name, flavor=self.flavor):
            return self._write()

    def _write(self) -> 'HLSModel':
        # fail-fast precondition mirroring RTLModel.write: a malformed or
        # interval-unsound program must not become a C++ kernel
        from ...analysis import codegen_verify_enabled, verify_or_raise

        if codegen_verify_enabled():
            verify_or_raise(self.solution, context=f'HLSModel.write({self.name!r}) precondition')
        src = self.path / 'src'
        src.mkdir(parents=True, exist_ok=True)
        (src / f'{self.name}.hh').write_text(emit_hls_kernel(self.solution, self.name, self.print_latency, self.flavor))
        shutil.copy(_SRC_DIR / 'dais_hls.hh', src / 'dais_hls.hh')
        (src / 'bridge.cc').write_text(self._emit_bridge())

        (self.path / 'model').mkdir(exist_ok=True)
        if self.is_pipeline:
            self.solution.save(self.path / 'model' / 'pipeline.json')
        else:
            self.solution.save(self.path / 'model' / 'comb.json')

        self._write_synth_files(src)

        lat_lo, lat_hi = self.solution.latency
        metadata = {
            'name': self.name,
            'flavor': self.flavor,
            'cost': self.solution.cost,
            'latency': [lat_lo, lat_hi],
            'clock_period': self.clock_period,
            'part': self.part,
            'pipelined': self.is_pipeline,
            'n_stages': len(self.solution.stages) if self.is_pipeline else 1,
            'inp_kifs': [tuple(int(v) for v in minimal_kif(q)) for q in self.solution.inp_qint],
            'out_kifs': [tuple(int(v) for v in minimal_kif(q)) for q in self.solution.out_qint],
        }
        (self.path / 'metadata.json').write_text(json.dumps(metadata, indent=2))
        return self

    def _write_synth_files(self, src: Path) -> None:
        """Per-flavor synthesis top + build script (the emulation path above
        is shared). Vendor tools are optional: scripts are emitted for use on
        a machine that has them (reference parity: hls_model.py:117-123)."""
        n_in = max(self.solution.shape[0], 1)
        n_out = max(self.solution.shape[1], 1)
        tdir = self.path / 'tcl'
        tdir.mkdir(exist_ok=True)
        if self.flavor == 'vitis':
            (tdir / 'build_vitis.tcl').write_text(
                f"""open_project -reset {self.name}_prj
set_top {self.name}_top
add_files src/{self.name}.hh
add_files src/dais_hls.hh
add_files src/hls_top.cc
open_solution -reset sol1
set_part {self.part}
create_clock -period {self.clock_period}
csynth_design
export_design -format ip_catalog
"""
            )
            (src / 'hls_top.cc').write_text(
                f'// Synthesis top: array interface around the inlined kernel.\n'
                f'#include "{self.name}.hh"\n'
                f'extern "C" void {self.name}_top(const int64_t in[{n_in}], int64_t out[{n_out}]) {{\n'
                f'#pragma HLS INTERFACE mode=ap_memory port=in\n'
                f'#pragma HLS INTERFACE mode=ap_memory port=out\n'
                f'    {self.name}(in, out);\n'
                f'}}\n'
            )
        elif self.flavor == 'hlslib':
            (src / 'hls_top.cc').write_text(
                f'// Intel HLS synthesis top: a component function (II pinned\n'
                f'// at the component level; the kernel body is loop-free).\n'
                f'#include "{self.name}.hh"\n'
                f'#include <HLS/hls.h>\n'
                f'hls_component_ii(1) component void {self.name}_top(const int64_t in[{n_in}], int64_t out[{n_out}]) {{\n'
                f'    {self.name}(in, out);\n'
                f'}}\n'
            )
            (tdir / 'build_hlslib.sh').write_text(
                f'#!/bin/sh\n# Intel HLS compiler flow (run where i++ is installed)\n'
                f'i++ -march="{self._intel_target()}" --clock {self.clock_period}ns -I src src/hls_top.cc -o {self.name}_prj\n'
            )
        else:  # oneapi
            (src / 'hls_top_oneapi.cpp').write_text(
                f'// oneAPI FPGA synthesis harness: SYCL single_task around the kernel.\n'
                f'#include <sycl/sycl.hpp>\n'
                f'#include "{self.name}.hh"\n'
                f'class {self.name}_kernel;\n'
                f'void {self.name}_top(sycl::queue& q, sycl::buffer<int64_t, 1>& b_in, sycl::buffer<int64_t, 1>& b_out) {{\n'
                f'    q.submit([&](sycl::handler& h) {{\n'
                f'        auto acc_in = b_in.get_access<sycl::access::mode::read>(h);\n'
                f'        auto acc_out = b_out.get_access<sycl::access::mode::write>(h);\n'
                f'        h.single_task<{self.name}_kernel>([=]() {{\n'
                f'            int64_t in[{n_in}], out[{n_out}];\n'
                f'            for (int e = 0; e < {n_in}; ++e) in[e] = acc_in[e];\n'
                f'            {self.name}(in, out);\n'
                f'            for (int e = 0; e < {n_out}; ++e) acc_out[e] = out[e];\n'
                f'        }});\n'
                f'    }});\n'
                f'}}\n'
            )
            (tdir / 'build_oneapi.sh').write_text(
                f'#!/bin/sh\n# oneAPI FPGA flow (run where icpx is installed)\n'
                f'icpx -fsycl -fintelfpga -Xshardware -Xstarget="{self._intel_target()}" '
                f'-I src src/hls_top_oneapi.cpp -o {self.name}_prj\n'
            )

    #: Intel FPGA family prefixes i++/icpx accept as -Xstarget values
    _INTEL_FAMILIES = ('agilex', 'arria', 'cyclone', 'stratix', 'max')

    def _intel_target(self) -> str:
        """Device target for the Intel flavors' build scripts.

        The class default ``part`` is an AMD Virtex part (the reference's
        default synthesis target); i++/icpx would reject it, so Intel-flavor
        scripts fall back to an Intel FPGA family unless the caller passed a
        recognizable Intel part. Unrecognized strings are substituted too
        (with a warning) rather than pasted into a build script that the
        Intel tools would reject.
        """
        part = self.part
        if part.lower().startswith(self._INTEL_FAMILIES):
            return part
        if not part.lower().startswith('xc'):
            import warnings

            warnings.warn(f'part {part!r} is not a recognizable Intel FPGA family; using Agilex7 in the Intel build script')
        return 'Agilex7'

    def _emit_bridge(self) -> str:
        in_f, in_w, in_s, out_f = self._io_consts()
        n_in, n_out = self.solution.shape

        def arr(vals):
            return '{' + ', '.join(str(v) for v in vals) + '}'

        return f"""// Generated emulation bridge: float64 batch in/out, OpenMP over samples.
#include <cmath>
#include <cstdint>
#include <omp.h>
#include "{self.name}.hh"

static const int N_IN = {n_in}, N_OUT = {n_out};
static const int IN_F[] = {arr(in_f)};
static const int IN_W[] = {arr(in_w)};
static const int IN_S[] = {arr(in_s)};
static const int OUT_F[] = {arr(out_f)};

extern "C" int inference(const double* in, double* out, long n_samples, int n_threads) {{
    if (n_threads <= 0) n_threads = omp_get_max_threads();
#pragma omp parallel for schedule(static) num_threads(n_threads)
    for (long s = 0; s < n_samples; ++s) {{
        int64_t codes[N_IN > 0 ? N_IN : 1], res[N_OUT > 0 ? N_OUT : 1];
        for (int e = 0; e < N_IN; ++e) {{
            int64_t v = int64_t(std::floor(std::ldexp(in[s * N_IN + e], IN_F[e])));
            codes[e] = da::wrap(v, IN_S[e], IN_W[e]);
        }}
        {self.name}(codes, res);
        for (int e = 0; e < N_OUT; ++e) out[s * N_OUT + e] = std::ldexp(double(res[e]), -OUT_F[e]);
    }}
    return 0;
}}
"""

    # ------------------------------------------------------------- compile

    def compile(self, verbose: bool = False) -> 'HLSModel':
        """Build the emulation .so with g++ (no vendor tools required)."""
        src = self.path / 'src'
        out = self.path / f'lib{self.name}_{uuid.uuid4().hex[:8]}.so'
        cxx = os.environ.get('CXX', 'g++')
        cmd = [cxx, '-std=c++17', '-O2', '-fPIC', '-shared', '-fopenmp', str(src / 'bridge.cc'), '-I', str(src), '-o', str(out)]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(f'HLS emulation build failed:\n{proc.stderr}')
        self._lib_path = out
        self._lib = None
        if verbose:
            telemetry.get_logger('codegen.hls').info(f'built {out}')
        return self

    def _load_lib(self) -> ctypes.CDLL:
        if self._lib is not None:
            return self._lib
        if self._lib_path is None:
            libs = sorted(self.path.glob(f'lib{self.name}_*.so'))
            if not libs:
                raise RuntimeError('HLS emulator not compiled; call compile() first')
            self._lib_path = libs[-1]
        lib = ctypes.CDLL(str(self._lib_path))
        lib.inference.restype = ctypes.c_int
        lib.inference.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_long,
            ctypes.c_int,
        ]
        self._lib = lib
        return lib

    # ------------------------------------------------------------- predict

    def predict(self, data: NDArray, backend: str = 'auto', n_threads: int = 0) -> NDArray[np.float64]:
        data = np.ascontiguousarray(np.asarray(data, dtype=np.float64).reshape(len(data), -1))
        if data.shape[1] != self.solution.shape[0]:
            raise ValueError(f'Input size mismatch: expected {self.solution.shape[0]}, got {data.shape[1]}')
        if backend == 'auto':
            try:
                self._load_lib()
                backend = 'emu'
            except RuntimeError:
                backend = 'interp'
        if backend == 'interp':
            return self.solution.predict(data)
        lib = self._load_lib()
        out = np.empty((len(data), self.solution.shape[1]), dtype=np.float64)
        if n_threads <= 0:
            n_threads = int(os.environ.get('DA_DEFAULT_THREADS', 0) or 0)
        rc = lib.inference(
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(data),
            n_threads,
        )
        if rc != 0:
            raise RuntimeError('HLS emulation inference failed')
        return out

    def __repr__(self) -> str:
        lat_lo, lat_hi = self.solution.latency
        kind = f'Pipeline[{len(self.solution.stages)}]' if self.is_pipeline else 'CombLogic'
        return f'HLSModel({self.name}: {kind}, estimated cost {self.solution.cost:.0f} LUTs, latency {lat_lo}-{lat_hi})'
