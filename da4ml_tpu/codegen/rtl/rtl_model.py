"""RTL project writer: Verilog (and VHDL) emission, Verilator emulation
binder, vendor build scripts, and bit-exact ``predict``.

``RTLModel`` takes a CombLogic or Pipeline, optionally re-times it to a
latency cutoff, and writes a self-contained project:

    <path>/
      src/            *.v stage modules + top + wrapper + primitives + .mem
      binder/         Verilator C++ binder + Makefile (emulation .so)
      tcl/            Vivado / Quartus out-of-context build scripts
      constraints/    clock constraints (.xdc / .sdc)
      model/          pipeline.json (reloadable IR)
      metadata.json   cost / latency / io-map summary

``predict`` runs the Verilator-compiled emulator when available
(``compile()``; requires verilator in PATH) and falls back to the bit-exact
DAIS interpreter with ``backend='interp'``.

Parity target: reference src/da4ml/codegen/rtl/rtl_model.py.
"""

from __future__ import annotations

import ctypes
import json
import os
import shutil
import subprocess
import uuid
from pathlib import Path

import numpy as np
from numpy.typing import NDArray

from ... import telemetry
from ...ir.comb import CombLogic, Pipeline
from ...ir.types import minimal_kif
from ..rtl.verilog.comb import VerilogCombEmitter
from ..rtl.verilog.io_wrapper import emit_io_wrapper
from ..rtl.verilog.pipeline import emit_pipeline

_SRC_DIR = Path(__file__).parent / 'verilog' / 'source'
_VHDL_SRC_DIR = Path(__file__).parent / 'vhdl' / 'source'
_COMMON_DIR = Path(__file__).parent / 'common'

PRIMITIVES = [
    'shift_adder.v',
    'negative.v',
    'quantizer.v',
    'relu.v',
    'msb_mux.v',
    'multiplier.v',
    'lookup_table.v',
    'bit_binop.v',
    'bit_unary.v',
]

VHDL_PRIMITIVES = [
    'da4ml_util.vhd',
    'shift_adder.vhd',
    'negative.vhd',
    'quantizer.vhd',
    'relu.vhd',
    'msb_mux.vhd',
    'multiplier.vhd',
    'lookup_table.vhd',
    'bit_binop.vhd',
    'bit_unary.vhd',
]


class RTLModel:
    """Write, build and drive one RTL project for a DAIS program."""

    flavor = 'verilog'
    # HDL name of the wrapper's output port ('out' is reserved in VHDL, so
    # the VHDL flavor renames it; the binder must address the same name).
    _hdl_out_port = 'out'

    def __init__(
        self,
        solution: CombLogic | Pipeline,
        name: str,
        path: str | Path,
        latency_cutoff: float = -1,
        print_latency: bool = False,
        part: str = 'xcvu13p-flga2577-2-e',
        clock_period: float = 5.0,
        clock_uncertainty: float = 0.1,
        register_layers: int = 1,
        io_delay_minmax: tuple[float, float] = (0.2, 0.4),
    ):
        if isinstance(solution, CombLogic) and latency_cutoff > 0:
            from ...trace.pipeline import to_pipeline

            solution = to_pipeline(solution, latency_cutoff)
        self.solution = solution
        self.name = name
        self.path = Path(path)
        self.print_latency = print_latency
        self.part = part
        self.clock_period = clock_period
        self.clock_uncertainty = clock_uncertainty
        self.register_layers = register_layers
        self.io_delay_minmax = io_delay_minmax
        self._lib: ctypes.CDLL | None = None
        self._lib_path: Path | None = None

    # ----------------------------------------------------------- properties

    @property
    def is_pipeline(self) -> bool:
        return isinstance(self.solution, Pipeline)

    @property
    def latency_ticks(self) -> int:
        """Clock ticks from input to output (register layers between stages)."""
        if not self.is_pipeline:
            return 0
        return (len(self.solution.stages) - 1) * max(self.register_layers, 1)

    @property
    def cost(self) -> float:
        return self.solution.cost

    # ------------------------------------------------------------ emission

    def _emit(self) -> tuple[dict[str, str], dict]:
        """Returns ({filename: text}, metadata)."""
        files: dict[str, str] = {}
        if self.is_pipeline:
            top_text, mem_files, stage_texts = emit_pipeline(
                self.solution, self.name, self.print_latency, self.register_layers
            )
            for si, text in enumerate(stage_texts):
                files[f'{self.name}_s{si}.v'] = text
            files[f'{self.name}.v'] = top_text
            files.update(mem_files)
            clocked = True
        else:
            em = VerilogCombEmitter(self.solution, self.name, self.print_latency)
            files[f'{self.name}.v'] = em.emit()
            files.update(em.mem_files)
            clocked = False

        wrapper_text, in_map, out_map = emit_io_wrapper(self.solution, f'{self.name}_wrapper', self.name, clocked)
        files[f'{self.name}_wrapper.v'] = wrapper_text

        inp_kifs = [tuple(int(v) for v in minimal_kif(q)) for q in self.solution.inp_qint]
        out_kifs = [tuple(int(v) for v in minimal_kif(q)) for q in self.solution.out_qint]
        lat_lo, lat_hi = self.solution.latency
        metadata = {
            'name': self.name,
            'flavor': self.flavor,
            'cost': self.solution.cost,
            'latency': [lat_lo, lat_hi],
            'latency_ticks': self.latency_ticks,
            'clock_period': self.clock_period,
            'clock_uncertainty': self.clock_uncertainty,
            'part': self.part,
            'pipelined': self.is_pipeline,
            'n_stages': len(self.solution.stages) if self.is_pipeline else 1,
            'reg_bits': self.solution.reg_bits if self.is_pipeline else 0,
            'inp_kifs': inp_kifs,
            'out_kifs': out_kifs,
            'in_lane_width': in_map.lane_width,
            'out_lane_width': out_map.lane_width,
            'in_elems': in_map.elems,
            'out_elems': out_map.elems,
        }
        return files, metadata

    def write(self) -> 'RTLModel':
        with telemetry.span('codegen.rtl.write', name=self.name, flavor=self.flavor):
            return self._write()

    def _write(self) -> 'RTLModel':
        # fail-fast precondition: refuse to emit HDL for a malformed or
        # interval-unsound program (set DA4ML_VERIFY=0 to bypass)
        from ...analysis import codegen_verify_enabled, verify_or_raise

        if codegen_verify_enabled():
            verify_or_raise(self.solution, context=f'{type(self).__name__}.write({self.name!r}) precondition')
        files, metadata = self._emit()
        src = self.path / 'src'
        src.mkdir(parents=True, exist_ok=True)
        for fname, text in files.items():
            (src / fname).write_text(text)
        prim_dir = _SRC_DIR if self.flavor == 'verilog' else _VHDL_SRC_DIR
        prims = PRIMITIVES if self.flavor == 'verilog' else VHDL_PRIMITIVES
        for prim in prims:
            shutil.copy(prim_dir / prim, src / prim)

        (self.path / 'model').mkdir(exist_ok=True)
        if self.is_pipeline:
            self.solution.save(self.path / 'model' / 'pipeline.json')
        else:
            self.solution.save(self.path / 'model' / 'comb.json')

        (self.path / 'metadata.json').write_text(json.dumps(metadata, indent=2))
        self._write_constraints()
        self._write_tcl()
        self._write_binder(metadata)
        return self

    def _subst(self, text: str) -> str:
        """Resolve @TOKEN@ placeholders in a flow/constraint template."""
        d_min, d_max = self.io_delay_minmax
        tokens = {
            'NAME': self.name,
            'PART': self.part,
            'FLAVOR': self.flavor,
            'CLOCK_PERIOD': str(self.clock_period),
            'UNCERTAINTY_SETUP': str(self.clock_uncertainty),
            'UNCERTAINTY_HOLD': str(self.clock_uncertainty),
            'DELAY_MIN': str(d_min),
            'DELAY_MAX': str(d_max),
        }
        for key, val in tokens.items():
            text = text.replace(f'@{key}@', val)
        return text

    def _write_constraints(self):
        cdir = self.path / 'constraints'
        cdir.mkdir(exist_ok=True)
        if self.is_pipeline:
            for ext in ('xdc', 'sdc'):
                template = (_COMMON_DIR / f'constraints.{ext}').read_text()
                (cdir / f'{self.name}.{ext}').write_text(self._subst(template))
        else:
            (cdir / f'{self.name}.xdc').write_text('# combinational block: no clock\n')

    def _write_tcl(self):
        tdir = self.path / 'tcl'
        tdir.mkdir(exist_ok=True)
        for vendor in ('vivado', 'quartus'):
            template = (_COMMON_DIR / f'{vendor}_flow.tcl').read_text()
            (tdir / f'build_{vendor}.tcl').write_text(self._subst(template))

    # ------------------------------------------------------------- binder

    def _write_binder(self, metadata: dict):
        bdir = self.path / 'binder'
        bdir.mkdir(exist_ok=True)
        shutil.copy(_COMMON_DIR / 'binder_util.hh', bdir / 'binder_util.hh')

        top = f'{self.name}_wrapper'
        lw_in, lw_out = metadata['in_lane_width'], metadata['out_lane_width']
        n_in, n_out = len(metadata['in_elems']), len(metadata['out_elems'])
        in_signed = [int(s) for _, _, s, _ in metadata['in_elems']]
        out_signed = [int(s) for _, _, s, _ in metadata['out_elems']]
        in_widths = [w for _, w, _, _ in metadata['in_elems']]
        out_widths = [w for _, w, _, _ in metadata['out_elems']]
        lat = metadata['latency_ticks']
        clocked = metadata['pipelined']

        def arr(vals):
            return '{' + ', '.join(str(v) for v in vals) + '}'

        binder = f"""// Generated Verilator binder for {top}: int64 codes in/out, OpenMP batch.
#include <omp.h>
#include <vector>
#include "V{top}.h"
#include "binder_util.hh"

using namespace da4ml_binder;

static const int N_IN = {n_in}, N_OUT = {n_out};
static const int LW_IN = {lw_in}, LW_OUT = {lw_out};
static const int LAT = {lat};
static const int IN_W[] = {arr(in_widths)};
static const int OUT_W[] = {arr(out_widths)};
static const int OUT_S[] = {arr(out_signed)};
static const int IN_S[] = {arr(in_signed)};

static void run_chunk(const int64_t* in, int64_t* out, long n) {{
    VerilatedContext ctx;
    V{top} top{{&ctx}};
"""
        outp = self._hdl_out_port
        if clocked:
            binder += f"""    long total = n + LAT;
    for (long t = 0; t < total; ++t) {{
        if (t < n)
            for (int e = 0; e < N_IN; ++e)
                set_bits(top.inp, e * LW_IN, IN_W[e] ? IN_W[e] : 1, uint64_t(in[t * N_IN + e]));
        top.clk = 0; top.eval();
        if (t >= LAT) {{
            long s = t - LAT;
            for (int e = 0; e < N_OUT; ++e)
                out[s * N_OUT + e] = sext(get_bits(top.{outp}, e * LW_OUT, OUT_W[e] ? OUT_W[e] : 1), OUT_W[e], OUT_S[e]);
        }}
        top.clk = 1; top.eval();
    }}
"""
        else:
            binder += f"""    for (long s = 0; s < n; ++s) {{
        for (int e = 0; e < N_IN; ++e)
            set_bits(top.inp, e * LW_IN, IN_W[e] ? IN_W[e] : 1, uint64_t(in[s * N_IN + e]));
        top.eval();
        for (int e = 0; e < N_OUT; ++e)
            out[s * N_OUT + e] = sext(get_bits(top.{outp}, e * LW_OUT, OUT_W[e] ? OUT_W[e] : 1), OUT_W[e], OUT_S[e]);
    }}
"""
        binder += """}

extern "C" int inference(const int64_t* in, int64_t* out, long n_samples, int n_threads) {
    if (n_threads <= 0) n_threads = omp_get_max_threads();
    long chunk = (n_samples + n_threads - 1) / n_threads;
    if (chunk < 32) chunk = 32;
    long n_chunks = (n_samples + chunk - 1) / chunk;
#pragma omp parallel for schedule(static) num_threads(n_threads)
    for (long c = 0; c < n_chunks; ++c) {
        long lo = c * chunk, hi = lo + chunk > n_samples ? n_samples : lo + chunk;
        run_chunk(in + lo * N_IN, out + lo * N_OUT, hi - lo);
    }
    return 0;
}
"""
        (bdir / 'binder.cc').write_text(binder)

        makefile = f"""TOP = {top}
VERILATOR ?= verilator
VERILATOR_ROOT ?= $(shell $(VERILATOR) --getenv VERILATOR_ROOT)
CXX ?= g++
SO = lib$(TOP).so

all: $(SO)

obj_dir/V$(TOP)__ALL.a: ../src/*.v
\t$(VERILATOR) --cc ../src/$(TOP).v -y ../src --Mdir obj_dir --build -j 0 -O3 --top-module $(TOP)

$(SO): binder.cc obj_dir/V$(TOP)__ALL.a
\t$(CXX) -O2 -fPIC -shared -fopenmp -std=c++17 -Iobj_dir -I$(VERILATOR_ROOT)/include \\
\t  binder.cc obj_dir/V$(TOP)__ALL.a \\
\t  $(VERILATOR_ROOT)/include/verilated.cpp $(VERILATOR_ROOT)/include/verilated_threads.cpp \\
\t  -o $(SO)

clean:
\trm -rf obj_dir $(SO)
"""
        (bdir / 'Makefile').write_text(makefile)

    # ------------------------------------------------------------- compile

    @staticmethod
    def emulation_available() -> bool:
        return shutil.which('verilator') is not None

    def compile(self, verbose: bool = False) -> 'RTLModel':
        """Build the Verilator emulation .so (requires verilator in PATH)."""
        if not self.emulation_available():
            raise RuntimeError('verilator not found in PATH; RTL emulation unavailable (use predict backend="interp")')
        bdir = self.path / 'binder'
        # copy .mem files next to the obj_dir so $readmemh resolves
        for mem in (self.path / 'src').glob('*.mem'):
            shutil.copy(mem, bdir / mem.name)
        env = os.environ.copy()
        proc = subprocess.run(['make', '-C', str(bdir)], capture_output=True, text=True, env=env)
        if proc.returncode != 0:
            raise RuntimeError(f'RTL emulation build failed:\n{proc.stdout}\n{proc.stderr}')
        built = bdir / f'lib{self.name}_wrapper.so'
        stamped = bdir / f'lib{self.name}_{uuid.uuid4().hex[:8]}.so'
        shutil.move(built, stamped)
        self._lib_path = stamped
        self._lib = None
        if verbose:
            telemetry.get_logger('codegen.rtl').info(f'built {stamped}')
        return self

    def _load_lib(self) -> ctypes.CDLL:
        if self._lib is not None:
            return self._lib
        if self._lib_path is None:
            libs = sorted((self.path / 'binder').glob(f'lib{self.name}_*.so'))
            if not libs:
                raise RuntimeError('emulator not compiled; call compile() first')
            self._lib_path = libs[-1]
        lib = ctypes.CDLL(str(self._lib_path))
        lib.inference.restype = ctypes.c_int
        lib.inference.argtypes = [
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_long,
            ctypes.c_int,
        ]
        self._lib = lib
        return lib

    # ------------------------------------------------------------- predict

    def _to_codes(self, data: NDArray) -> NDArray[np.int64]:
        """Float inputs -> integer codes: wrap(floor(x * 2**(inp_shift + f)))."""
        first = self.solution.stages[0] if self.is_pipeline else self.solution
        codes = np.empty(data.shape, dtype=np.int64)
        for e, qi in enumerate(self.solution.inp_qint):
            k, i, f = minimal_kif(qi)
            w = k + i + f
            v = np.floor(data[:, e] * 2.0 ** (f + int(first.inp_shifts[e]))).astype(np.int64)
            if w <= 0:
                codes[:, e] = 0
                continue
            mod = np.int64(1) << w
            int_min = -(np.int64(1) << (w - 1)) if k else np.int64(0)
            codes[:, e] = (((v - int_min) % mod) + int_min) & (mod - 1)
        return codes

    def _from_codes(self, codes: NDArray[np.int64]) -> NDArray[np.float64]:
        out = np.empty(codes.shape, dtype=np.float64)
        for e, qi in enumerate(self.solution.out_qint):
            _, _, f = minimal_kif(qi)
            out[:, e] = codes[:, e].astype(np.float64) * 2.0**-f
        return out

    def predict(self, data: NDArray, backend: str = 'auto', n_threads: int = 0) -> NDArray[np.float64]:
        """Bit-exact inference: 'emu' (Verilator .so), 'interp' (DAIS),
        'netlist' (execute the emitted HDL in the bundled simulator — the
        clocked pipelined top for pipelines), or 'auto'."""
        data = np.asarray(data, dtype=np.float64).reshape(len(data), -1)
        if backend == 'auto':
            try:
                self._load_lib()
                backend = 'emu'
            except RuntimeError:
                backend = 'interp'
        if backend == 'interp':
            return self.solution.predict(data)
        if backend == 'netlist':
            if self.flavor == 'verilog':
                from .verilog.netlist_sim import simulate_comb, simulate_pipeline

                if self.is_pipeline:
                    return simulate_pipeline(self.solution, self.name, data, self.register_layers)
                return simulate_comb(self.solution, self.name, data)
            from .vhdl.netlist_sim import simulate_comb_vhdl, simulate_pipeline_vhdl

            if self.is_pipeline:
                return simulate_pipeline_vhdl(self.solution, self.name, data, self.register_layers)
            return simulate_comb_vhdl(self.solution, self.name, data)
        lib = self._load_lib()
        codes = np.ascontiguousarray(self._to_codes(data))
        out = np.empty((len(data), len(self.solution.out_qint)), dtype=np.int64)
        if n_threads <= 0:
            n_threads = int(os.environ.get('DA_DEFAULT_THREADS', 0) or 0)
        rc = lib.inference(
            codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(data),
            n_threads,
        )
        if rc != 0:
            raise RuntimeError('RTL emulation inference failed')
        return self._from_codes(out)

    def __repr__(self) -> str:
        lat_lo, lat_hi = self.solution.latency
        kind = f'Pipeline[{len(self.solution.stages)}]' if self.is_pipeline else 'CombLogic'
        return (
            f'{type(self).__name__}({self.name}: {kind}, estimated cost {self.cost:.0f} LUTs, '
            f'latency {lat_lo}-{lat_hi}, {self.latency_ticks} ticks @ {self.clock_period} ns)'
        )


class VerilogModel(RTLModel):
    flavor = 'verilog'


class VHDLModel(RTLModel):
    """VHDL-2008 flavor: same project layout with .vhd sources.

    The emulation path GHDL-synthesizes the VHDL to Verilog first (see the
    binder Makefile); where GHDL is absent the bundled VHDL netlist
    simulator (vhdl/netlist_sim.py) provides the generated-code oracle.
    """

    flavor = 'vhdl'
    _hdl_out_port = 'out_port'

    def _emit(self):
        from .vhdl.comb import VHDLCombEmitter
        from .vhdl.io_wrapper import emit_io_wrapper_vhdl
        from .vhdl.pipeline import emit_pipeline_vhdl

        files: dict[str, str] = {}
        if self.is_pipeline:
            top_text, mem_files, stage_texts = emit_pipeline_vhdl(
                self.solution, self.name, self.print_latency, self.register_layers
            )
            for si, text in enumerate(stage_texts):
                files[f'{self.name}_s{si}.vhd'] = text
            files[f'{self.name}.vhd'] = top_text
            files.update(mem_files)
            clocked = True
        else:
            em = VHDLCombEmitter(self.solution, self.name, self.print_latency)
            files[f'{self.name}.vhd'] = em.emit()
            files.update(em.mem_files)
            clocked = False

        wrapper_text, in_map, out_map = emit_io_wrapper_vhdl(self.solution, f'{self.name}_wrapper', self.name, clocked)
        files[f'{self.name}_wrapper.vhd'] = wrapper_text

        inp_kifs = [tuple(int(v) for v in minimal_kif(q)) for q in self.solution.inp_qint]
        out_kifs = [tuple(int(v) for v in minimal_kif(q)) for q in self.solution.out_qint]
        lat_lo, lat_hi = self.solution.latency
        metadata = {
            'name': self.name,
            'flavor': self.flavor,
            'cost': self.solution.cost,
            'latency': [lat_lo, lat_hi],
            'latency_ticks': self.latency_ticks,
            'clock_period': self.clock_period,
            'clock_uncertainty': self.clock_uncertainty,
            'part': self.part,
            'pipelined': self.is_pipeline,
            'n_stages': len(self.solution.stages) if self.is_pipeline else 1,
            'reg_bits': self.solution.reg_bits if self.is_pipeline else 0,
            'inp_kifs': inp_kifs,
            'out_kifs': out_kifs,
            'in_lane_width': in_map.lane_width,
            'out_lane_width': out_map.lane_width,
            'in_elems': in_map.elems,
            'out_elems': out_map.elems,
        }
        return files, metadata

    def _write_binder(self, metadata: dict):
        super()._write_binder(metadata)
        # GHDL-synthesize the VHDL to Verilog before the Verilator step
        bdir = self.path / 'binder'
        top = f'{self.name}_wrapper'
        # GHDL analyzes in command-line order: util + primitives first, then
        # stages (instantiated by the top), then the top, then the wrapper.
        srcs = ['da4ml_util.vhd'] + [p for p in VHDL_PRIMITIVES if p != 'da4ml_util.vhd']
        if self.is_pipeline:
            srcs += [f'{self.name}_s{si}.vhd' for si in range(len(self.solution.stages))]
        srcs += [f'{self.name}.vhd', f'{self.name}_wrapper.vhd']
        src_list = ' '.join(f'../src/{s}' for s in srcs)
        makefile = f"""TOP = {top}
VERILATOR ?= verilator
VERILATOR_ROOT ?= $(shell $(VERILATOR) --getenv VERILATOR_ROOT)
GHDL ?= ghdl
CXX ?= g++
SO = lib$(TOP).so
SRCS = {src_list}

all: $(SO)

$(TOP).v: $(SRCS)
\t$(GHDL) -a --std=08 $(SRCS)
\t$(GHDL) synth --std=08 --out=verilog $(TOP) > $(TOP).v

obj_dir/V$(TOP)__ALL.a: $(TOP).v
\t$(VERILATOR) --cc $(TOP).v --Mdir obj_dir --build -j 0 -O3 --top-module $(TOP)

$(SO): binder.cc obj_dir/V$(TOP)__ALL.a
\t$(CXX) -O2 -fPIC -shared -fopenmp -std=c++17 -Iobj_dir -I$(VERILATOR_ROOT)/include \\
\t  binder.cc obj_dir/V$(TOP)__ALL.a \\
\t  $(VERILATOR_ROOT)/include/verilated.cpp $(VERILATOR_ROOT)/include/verilated_threads.cpp \\
\t  -o $(SO)

clean:
\trm -rf obj_dir $(SO) $(TOP).v work-obj08.cf
"""
        (bdir / 'Makefile').write_text(makefile)

    @staticmethod
    def emulation_available() -> bool:
        return shutil.which('verilator') is not None and shutil.which('ghdl') is not None
