# Timing constraints (Quartus / generic SDC). Tokens resolved at
# project-write time; uncertainty and IO delays are ratios of the period.
set period @CLOCK_PERIOD@

create_clock -period $period -name clk [get_ports {clk}]

set_clock_uncertainty -setup -to [get_clocks clk] [expr {$period * @UNCERTAINTY_SETUP@}]
set_clock_uncertainty -hold  -to [get_clocks clk] [expr {$period * @UNCERTAINTY_HOLD@}]

set_input_delay  -clock clk -max [expr {$period * @DELAY_MAX@}] [get_ports {inp[*]}]
set_input_delay  -clock clk -min [expr {$period * @DELAY_MIN@}] [get_ports {inp[*]}]
set_output_delay -clock clk -max [expr {$period * @DELAY_MAX@}] [get_ports {out[*]}]
set_output_delay -clock clk -min [expr {$period * @DELAY_MIN@}] [get_ports {out[*]}]
