# Timing constraints (Vivado OOC). Tokens resolved at project-write time;
# uncertainty and IO delays are ratios of the clock period.
set period @CLOCK_PERIOD@

create_clock -period $period -name clk [get_ports clk]

set_clock_uncertainty -setup [expr {$period * @UNCERTAINTY_SETUP@}] [get_clocks clk]
set_clock_uncertainty -hold  [expr {$period * @UNCERTAINTY_HOLD@}]  [get_clocks clk]

set_input_delay  -clock clk -max [expr {$period * @DELAY_MAX@}] [get_ports {inp[*]}]
set_input_delay  -clock clk -min [expr {$period * @DELAY_MIN@}] [get_ports {inp[*]}]
set_output_delay -clock clk -max [expr {$period * @DELAY_MAX@}] [get_ports {out[*]}]
set_output_delay -clock clk -min [expr {$period * @DELAY_MIN@}] [get_ports {out[*]}]
