# Out-of-context synthesis -> implementation flow (Vivado), staged with
# checkpoints and per-stage reports. Substitution tokens (@NAME@, @PART@,
# @FLAVOR@) are resolved by rtl_model.py at project-write time; every report
# lands in reports/ under the names `da4ml-tpu report` parses.
#
# Capability parity with the reference OOC flow
# (src/da4ml/codegen/rtl/common_source/build_vivado_prj.tcl of calad0i/da4ml).

set name   "@NAME@"
set part   "@PART@"
set flavor "@FLAVOR@"

set root    [file normalize [file dirname [info script]]/..]
set out_dir "$root/build_$name"
set rpt_dir "$out_dir/reports"
file mkdir $out_dir
file mkdir $rpt_dir

create_project -in_memory -part $part

if { $flavor eq "vhdl" } {
    set_property TARGET_LANGUAGE VHDL [current_project]
    foreach f [glob -nocomplain "$root/src/*.vhd"] { read_vhdl -vhdl2008 $f }
} else {
    set_property TARGET_LANGUAGE Verilog [current_project]
    set srcs [glob -nocomplain "$root/src/*.v"]
    if { [llength $srcs] > 0 } { read_verilog $srcs }
}

# lookup-table images must be visible to synthesis ($readmemh)
foreach f [glob -nocomplain "$root/src/*.mem"] {
    add_files -fileset [current_fileset] $f
    set_property used_in_synthesis true [get_files $f]
}

if { [file exists "$root/constraints/$name.xdc"] } {
    read_xdc -mode out_of_context "$root/constraints/$name.xdc"
}

set top "${name}_wrapper"

# -- synthesis ---------------------------------------------------------------
synth_design -top $top -mode out_of_context -flatten_hierarchy full \
    -resource_sharing auto -directive AreaOptimized_High -global_retiming on
write_checkpoint -force "$out_dir/${name}_synth.dcp"
report_timing_summary -file "$rpt_dir/${name}_post_synth_timing.rpt"
report_utilization    -file "$rpt_dir/${name}_post_synth_util.rpt"
report_power          -file "$rpt_dir/${name}_post_synth_power.rpt"

# -- implementation ----------------------------------------------------------
opt_design -directive ExploreWithRemap
place_design -fanout_opt
phys_opt_design -directive AggressiveExplore
write_checkpoint -force "$out_dir/${name}_place.dcp"
file delete -force "$out_dir/${name}_synth.dcp"
report_timing_summary -file "$rpt_dir/${name}_post_place_timing.rpt"

route_design -directive NoTimingRelaxation
write_checkpoint -force "$out_dir/${name}_route.dcp"
file delete -force "$out_dir/${name}_place.dcp"

# -- final reports (parsed by the report CLI) --------------------------------
report_timing_summary     -file "$rpt_dir/${name}_post_route_timing.rpt"
report_timing -sort_by group -max_paths 100 -path_type summary \
                          -file "$rpt_dir/${name}_post_route_timing_paths.rpt"
report_utilization        -file "$rpt_dir/${name}_post_route_util.rpt"
report_utilization -format xml -hierarchical \
                          -file "$rpt_dir/${name}_post_route_util.xml"
report_clock_utilization  -file "$rpt_dir/${name}_post_route_clock_util.rpt"
report_power              -file "$rpt_dir/${name}_post_route_power.rpt"
report_drc                -file "$rpt_dir/${name}_post_route_drc.rpt"

puts "da4ml-tpu: implementation done, reports in $rpt_dir"
