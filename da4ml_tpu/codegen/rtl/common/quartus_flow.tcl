# Quartus out-of-context compile flow: virtual pins (no package pin
# assignment), timing-driven synthesis, full compile, reports collected into
# reports/. Substitution tokens resolved by rtl_model.py at write time.
#
# Capability parity with the reference flow
# (src/da4ml/codegen/rtl/common_source/build_quartus_prj.tcl of calad0i/da4ml).

set name   "@NAME@"
set device "@PART@"
set flavor "@FLAVOR@"

set root    [file normalize [file dirname [info script]]/..]
set out_dir "$root/build_$name"
set rpt_dir "$out_dir/reports"
file mkdir $out_dir
file mkdir $rpt_dir
cd $out_dir

load_package flow

project_new $name -overwrite -revision $name
set_global_assignment -name FAMILY [lindex [split $device "-"] 0]
set_global_assignment -name DEVICE $device
set_global_assignment -name TOP_LEVEL_ENTITY "${name}_wrapper"
set_global_assignment -name PROJECT_OUTPUT_DIRECTORY $out_dir

if { $flavor eq "vhdl" } {
    set_global_assignment -name VHDL_INPUT_VERSION VHDL_2008
    foreach f [glob -nocomplain "$root/src/*.vhd"] {
        set_global_assignment -name VHDL_FILE $f
    }
} else {
    foreach f [glob -nocomplain "$root/src/*.v"] {
        set_global_assignment -name VERILOG_FILE $f
    }
}
foreach f [glob -nocomplain "$root/src/*.mem"] {
    file copy -force $f "$out_dir/[file tail $f]"
}
if { [file exists "$root/constraints/$name.sdc"] } {
    file copy -force "$root/constraints/$name.sdc" "$out_dir/$name.sdc"
    set_global_assignment -name SDC_FILE "$out_dir/$name.sdc"
}

# out-of-context: run analysis & synthesis once, then pin every top-level
# port to a virtual pin so the fitter never touches the package
execute_module -tool map
foreach_in_collection pin [get_names -filter * -node_type pin] {
    set_instance_assignment -to [get_name_info -info full_path $pin] -name VIRTUAL_PIN ON
}
export_assignments

set_global_assignment -name OPTIMIZATION_MODE "HIGH PERFORMANCE EFFORT"
set_global_assignment -name OPTIMIZATION_TECHNIQUE SPEED
set_global_assignment -name AUTO_RESOURCE_SHARING ON
set_global_assignment -name ALLOW_REGISTER_RETIMING ON
set_global_assignment -name SYNTH_TIMING_DRIVEN_SYNTHESIS ON
set_global_assignment -name TIMEQUEST_MULTICORNER_ANALYSIS ON
set_global_assignment -name FITTER_EFFORT "STANDARD FIT"

execute_flow -compile

foreach f [glob -nocomplain "$out_dir/*.rpt"] {
    file copy -force $f "$rpt_dir/"
}
project_close

puts "da4ml-tpu: compile done, reports in $rpt_dir"
