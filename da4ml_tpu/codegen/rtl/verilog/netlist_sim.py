"""Bit-exact netlist simulator for the emitted Verilog subset.

Parses the text produced by :class:`VerilogCombEmitter` (wire declarations,
assigns with slices, primitive instantiations, $readmemh tables) and evaluates
it sample by sample with two's-complement integer semantics. This provides a
true generated-code oracle on hosts without verilator/ghdl: the simulator
executes the emitted netlist, not the IR it came from.

Primitive semantics mirror the modules in ``source/*.v`` bit for bit.
"""

from __future__ import annotations

import re

import numpy as np
from numpy.typing import NDArray


def _mask(w: int) -> int:
    return (1 << w) - 1


def _sext(v: int, w: int) -> int:
    v &= _mask(w)
    return v - (1 << w) if w > 0 and (v >> (w - 1)) & 1 else v


def _shr(v: int, s: int) -> int:
    return v >> s  # python >> is arithmetic on ints


class _Instance:
    def __init__(self, prim: str, params: dict[str, int | str], ports: dict[str, str]):
        self.prim = prim
        self.params = params
        self.ports = ports


_RE_WIRE = re.compile(r'wire\s+(signed\s+)?\[(\d+):0\]\s+(\w+)\s*(?:=\s*(.+?))?;')
_RE_WIRE1 = re.compile(r'wire\s+(\w+)\s*=\s*(.+?);')
_RE_ASSIGN = re.compile(r'assign\s+(\w+)(?:\[(\d+):(\d+)\])?\s*=\s*(.+?);')
_RE_INST = re.compile(r'(\w+)\s*#\((.*?)\)\s*(\w+)\s*\((.*?)\);')
_RE_KV = re.compile(r'\.(\w+)\(([^()]*(?:\([^()]*\))?[^()]*)\)')


class VerilogNetlistSim:
    """Simulate one emitted combinational module."""

    def __init__(self, text: str, mem_files: dict[str, str]):
        self.wire_width: dict[str, int] = {}
        self.wire_signed: dict[str, bool] = {}
        self.exprs: list[tuple[str, tuple[int, int] | None, str]] = []  # (lhs, slice, rhs)
        self.instances: list[_Instance] = []
        self.mem: dict[str, list[int | None]] = {}
        for fname, content in mem_files.items():
            entries: list[int | None] = []
            for line in content.strip().splitlines():
                line = line.strip()
                entries.append(None if 'x' in line else int(line, 16))
            self.mem[fname] = entries

        # a regex miss here would silently mask all I/O to zero width —
        # refuse to simulate unparsed ports, like every other construct
        m = re.search(r'input\s+\[(\d+):0\]\s+inp', text)
        if not m:
            raise ValueError('Unparsed module ports: no `input [hi:0] inp` declaration found')
        self.in_width = int(m.group(1)) + 1
        m = re.search(r'output\s+\[(\d+):0\]\s+out', text)
        if not m:
            raise ValueError('Unparsed module ports: no `output [hi:0] out` declaration found')
        self.out_width = int(m.group(1)) + 1

        body = text[text.index(');') + 2 :]
        for raw in body.splitlines():
            line = raw.split('//')[0].strip()
            if not line or line == 'endmodule':
                continue
            if line.startswith('wire'):
                mw = _RE_WIRE.match(line)
                if mw:
                    signed, hi, name, rhs = mw.group(1), int(mw.group(2)), mw.group(3), mw.group(4)
                    self.wire_width[name] = hi + 1
                    self.wire_signed[name] = bool(signed)
                    if rhs:
                        self.exprs.append((name, None, rhs.strip()))
                    continue
                m1 = _RE_WIRE1.match(line)
                if m1:
                    self.wire_width[m1.group(1)] = 1
                    self.wire_signed[m1.group(1)] = False
                    self.exprs.append((m1.group(1), None, m1.group(2).strip()))
                    continue
                raise ValueError(f'Unparsed wire: {line}')
            if line.startswith('assign'):
                ma = _RE_ASSIGN.match(line)
                if not ma:
                    raise ValueError(f'Unparsed assign: {line}')
                lhs, hi, lo, rhs = ma.groups()
                sl = (int(hi), int(lo)) if hi is not None else None
                self.exprs.append((lhs, sl, rhs.strip()))
                continue
            mi = _RE_INST.match(line)
            if mi:
                prim, params_s, _iname, ports_s = mi.groups()
                params: dict[str, int | str] = {}
                for k, v in _RE_KV.findall(params_s):
                    v = v.strip()
                    params[k] = v.strip('"') if v.startswith('"') else int(v)
                ports = {k: v.strip() for k, v in _RE_KV.findall(ports_s)}
                self.instances.append(_Instance(prim, params, ports))
                continue
            raise ValueError(f'Unparsed line: {line}')

    # ------------------------------------------------------------- evaluate

    def _eval_rhs(self, rhs: str, env: dict[str, int]) -> int:
        rhs = rhs.strip()
        m = re.fullmatch(r'(\w+)\[(\d+):(\d+)\]', rhs)
        if m:
            name, hi, lo = m.group(1), int(m.group(2)), int(m.group(3))
            v = env[name] if name != 'inp' else env['inp']
            return (v >> lo) & _mask(hi - lo + 1)
        m = re.fullmatch(r"(\d+)'s?d(\d+)", rhs)
        if m:
            return int(m.group(2)) & _mask(int(m.group(1)))
        m = re.fullmatch(r"1'b([01])", rhs)
        if m:
            return int(m.group(1))
        m = re.fullmatch(r"-(\d+)'sd(\d+)", rhs)
        if m:
            return -int(m.group(2))
        m = re.fullmatch(r'\$signed\((\w+)\)', rhs)
        if m:
            name = m.group(1)
            return _sext(env[name], self.wire_width[name])
        m = re.fullmatch(r"\$signed\(\{1'b0, (\w+)\}\)", rhs)
        if m:
            return env[m.group(1)] & _mask(self.wire_width[m.group(1)])
        m = re.fullmatch(r'\(\((\w+) <<< (\d+)\) >>> (\d+)\) \+ (.+)', rhs)
        if m:
            base = self._signed_value(m.group(1))
            shifted = _shr(base << int(m.group(2)), int(m.group(3)))
            return shifted + self._eval_rhs(m.group(4), {**self._env, **{}})
        if re.fullmatch(r'\w+', rhs):
            return self._env[rhs] if rhs in self._env else env[rhs]
        raise ValueError(f'Unparsed rhs: {rhs}')

    def _signed_value(self, name: str) -> int:
        v = self._env[name]
        w = self.wire_width[name]
        return _sext(v, w) if self.wire_signed.get(name, False) else v

    def run_sample(self, inp_bits: int) -> int:
        env: dict[str, int] = {'inp': inp_bits}
        self._env = env
        out_val = 0

        # exprs and instances are interleaved in the source and reference only
        # earlier wires; iterate to a fixed point, deferring entries whose
        # operands aren't computed yet (KeyError)
        pending = [('expr', e) for e in self.exprs] + [('inst', i) for i in self.instances]
        max_rounds = len(pending) + 2
        for _ in range(max_rounds):
            if not pending:
                break
            next_pending = []
            for kind, item in pending:
                try:
                    if kind == 'expr':
                        lhs, sl, rhs = item
                        val = self._eval_rhs(rhs, env)
                        if lhs == 'out':
                            hi, lo = sl if sl else (self.out_width - 1, 0)
                            w = hi - lo + 1
                            out_val |= (val & _mask(w)) << lo
                        else:
                            w = self.wire_width.get(lhs, 64)
                            env[lhs] = val & _mask(w)
                    else:
                        self._run_instance(item, env)
                except KeyError:
                    next_pending.append((kind, item))
            pending = next_pending
        if pending:
            raise RuntimeError(f'Unresolved netlist elements: {pending[:3]}')
        return out_val

    def _run_instance(self, inst: _Instance, env: dict[str, int]):
        p = inst.params
        g = lambda name: env[inst.ports[name]]  # raises KeyError if not ready

        def sval(name, w, signed):
            return _sext(env[inst.ports[name]], w) if signed else env[inst.ports[name]] & _mask(w)

        prim = inst.prim
        if prim == 'shift_adder':
            a = sval('a', p['WA'], p['SA'])
            b = sval('b', p['WB'], p['SB'])
            s = (a << p['SHA']) - (b << p['SHB']) if p['SUB'] else (a << p['SHA']) + (b << p['SHB'])
            r = _shr(s, p['GSHIFT'])
        elif prim == 'negative':
            r = -sval('a', p['WA'], p['SA'])
        elif prim == 'quantizer':
            v = sval('a', p['WA'], p['SA'])
            if p['NEG']:
                v = -v
            sh = p['SHIFT']
            r = v << sh if sh >= 0 else _shr(v, -sh)
        elif prim == 'relu':
            v = sval('a', p['WA'], p['SA'])
            if p['NEG']:
                v = -v
            sh = p['SHIFT']
            q = v << sh if sh >= 0 else _shr(v, -sh)
            r = 0 if v < 0 else q
        elif prim == 'msb_mux':
            c = env[inst.ports['c']]
            sel = (c >> (p['WC'] - 1)) & 1
            a = sval('a', p['WA'], p['SA'])
            b = sval('b', p['WB'], p['SB'])
            if p['NEG_B']:
                b = -b
            r0 = a << p['SH0'] if p['SH0'] >= 0 else _shr(a, -p['SH0'])
            r1 = b << p['SH1'] if p['SH1'] >= 0 else _shr(b, -p['SH1'])
            r = r0 if sel else r1
        elif prim == 'multiplier':
            r = sval('a', p['WA'], p['SA']) * sval('b', p['WB'], p['SB'])
        elif prim == 'lookup_table':
            addr = env[inst.ports['a']] & _mask(p['WA'])
            table = self.mem[str(p['MEMFILE'])]
            entry = table[addr]
            if entry is None:
                raise RuntimeError(f'lookup hit unreachable entry {addr}')
            r = entry
        elif prim == 'bit_unary':
            v = sval('a', p['WA'], p['SA'])
            if p['NEG']:
                v = -v
            vw = v & _mask(p['W0'])
            if p['OP'] == 0:
                r = ~v
            elif p['OP'] == 1:
                r = int(vw != 0)
            else:
                r = int(vw == _mask(p['W0']))
        elif prim == 'bit_binop':
            a = sval('a', p['WA'], p['SA'])
            b = sval('b', p['WB'], p['SB'])
            if p['NEG_A']:
                a = -a
            if p['NEG_B']:
                b = -b
            a <<= p['SHA']
            b <<= p['SHB']
            r = a & b if p['OP'] == 0 else (a | b if p['OP'] == 1 else a ^ b)
        else:
            raise ValueError(f'Unknown primitive {prim}')
        env[inst.ports['o']] = r & _mask(p['WO'])


def pack_inputs(in_lay, comb, data: NDArray) -> list[int]:
    """Pack float samples into the wrapper's input bit lanes."""
    from ....ir.types import minimal_kif

    inp_kifs = [minimal_kif(q) for q in comb.inp_qint]
    packed: list[int] = []
    for row in np.asarray(data, dtype=np.float64):
        bits = 0
        for e, (off, w) in enumerate(in_lay):
            if w == 0:
                continue
            k, i, f = inp_kifs[e]
            v = int(np.floor(row[e] * 2.0 ** (f + int(comb.inp_shifts[e]))))
            bits |= (v & _mask(w)) << off
        packed.append(bits)
    return packed


def descale_outputs(out_lay, comb, out_bits_seq) -> NDArray[np.float64]:
    """Unpack raw output bits into floats, same interpretation as predict."""
    from ....ir.types import minimal_kif

    out_kifs = [minimal_kif(q) for q in comb.out_qint]
    out = np.zeros((len(out_bits_seq), comb.shape[1]), dtype=np.float64)
    for s, out_bits in enumerate(out_bits_seq):
        for e, (off, w) in enumerate(out_lay):
            if w == 0:
                continue
            k, i, f = out_kifs[e]
            raw = (out_bits >> off) & _mask(w)
            out[s, e] = float(_sext(raw, w) if k else raw) * 2.0**-f
    return out


def run_netlist(em, sim, comb, data: NDArray) -> NDArray[np.float64]:
    """Pack samples into wrapper bit lanes, run `sim`, descale the outputs.

    Shared by the Verilog and VHDL flavors; the returned values use the same
    output interpretation as ``CombLogic.predict``, so results are directly
    comparable.
    """
    packed = pack_inputs(em.input_layout(), comb, data)
    out_bits = [sim.run_sample(bits) for bits in packed]
    return descale_outputs(em.output_layout(), comb, out_bits)


class PipelineNetlistSim:
    """Clock-accurate simulator for the emitted II=1 pipelined top module.

    Executes the registered *top-module text* — stage instances evaluate
    through the per-stage netlist simulators, and the `always @(posedge clk)`
    (resp. ``rising_edge(clk)``) registers latch with nonblocking semantics.
    One new sample is fed every clock (II=1) and outputs are read after the
    pipeline's register latency, mirroring the clocked `_inference` loop of
    the reference's Verilator binder (reference
    codegen/rtl/common_source/binder_util.hh:11-40).

    The parsed structure is flavor-agnostic: subclasses fill ``aliases``
    (continuous lhs = src), ``insts`` [(stage_sim, in_wire, out_wire)],
    ``regs`` {reg: src}, and ``out_src``.
    """

    aliases: list[tuple[str, str]]
    insts: list[tuple[VerilogNetlistSim, str, str]]
    regs: dict[str, str]
    out_src: str
    in_width: int
    out_width: int

    @property
    def latency_ticks(self) -> int:
        """Clock cycles from a sample entering to its result on `out`."""
        return len(self.regs)

    def _settle(self, env: dict[str, int]) -> None:
        pending = [('alias', a) for a in self.aliases] + [('inst', i) for i in self.insts]
        for _ in range(len(pending) + 2):
            if not pending:
                return
            nxt = []
            for kind, item in pending:
                try:
                    if kind == 'alias':
                        lhs, src = item
                        env[lhs] = env[src]
                    else:
                        sim, iw, ow = item
                        env[ow] = sim.run_sample(env[iw])
                except KeyError:
                    nxt.append((kind, item))
            pending = nxt
        if pending:
            raise RuntimeError(f'Unresolved top-module elements: {pending[:3]}')

    def run_stream(self, samples: list[int]) -> list[int]:
        """Feed one sample per rising edge; return one output per sample."""
        regs = dict.fromkeys(self.regs, 0)
        lat = self.latency_ticks
        outs: list[int] = []
        for t in range(len(samples) + lat):
            env = dict(regs)
            env['inp'] = (samples[t] & _mask(self.in_width)) if t < len(samples) else 0
            self._settle(env)
            if t >= lat:
                outs.append(env[self.out_src] & _mask(self.out_width))
            # nonblocking: every register samples its source from this cycle
            regs = {r: env[src] for r, src in self.regs.items()}
        return outs


_RE_TOP_ALIAS = re.compile(r'wire\s+\[(\d+):0\]\s+(\w+)\s*=\s*(\w+);')
_RE_TOP_DECL = re.compile(r'(?:wire|reg)\s+\[(\d+):0\]\s+(\w+);')
_RE_TOP_FF = re.compile(r'always\s*@\(posedge clk\)\s+(\w+)\s*<=\s*(\w+);')
_RE_TOP_INST = re.compile(r'(\w+)\s+(\w+)\s*\(\s*\.inp\((\w+)\),\s*\.out\((\w+)\)\s*\);')
_RE_TOP_OUT = re.compile(r'assign\s+out\s*=\s*(\w+);')


class VerilogPipelineSim(PipelineNetlistSim):
    """Parse + simulate the Verilog pipelined top emitted by emit_pipeline."""

    def __init__(self, top_text: str, stage_texts: list[str], mem_files: dict[str, str]):
        stage_sims: dict[str, VerilogNetlistSim] = {}
        for t in stage_texts:
            mname = re.search(r'module\s+(\w+)', t).group(1)
            stage_sims[mname] = VerilogNetlistSim(t, mem_files)

        self.aliases, self.insts, self.regs = [], [], {}
        self.out_src = ''
        # a miss here used to fall back to width 0, masking all I/O to zero;
        # unparsed ports must fail loudly like unparsed body lines
        m = re.search(r'input\s+\[(\d+):0\]\s+inp', top_text)
        if not m:
            raise ValueError('Unparsed pipelined top ports: no `input [hi:0] inp` declaration found')
        self.in_width = int(m.group(1)) + 1
        m = re.search(r'output\s+\[(\d+):0\]\s+out', top_text)
        if not m:
            raise ValueError('Unparsed pipelined top ports: no `output [hi:0] out` declaration found')
        self.out_width = int(m.group(1)) + 1

        body = top_text[top_text.index(');') + 2 :]
        for raw in body.splitlines():
            line = raw.split('//')[0].strip()
            if not line or line == 'endmodule':
                continue
            if m := _RE_TOP_ALIAS.match(line):
                self.aliases.append((m.group(2), m.group(3)))
            elif _RE_TOP_DECL.match(line):
                pass  # width declaration only
            elif m := _RE_TOP_FF.match(line):
                self.regs[m.group(1)] = m.group(2)
            elif m := _RE_TOP_INST.match(line):
                self.insts.append((stage_sims[m.group(1)], m.group(3), m.group(4)))
            elif m := _RE_TOP_OUT.match(line):
                self.out_src = m.group(1)
            else:
                raise ValueError(f'Unparsed top-module line: {line}')
        if not self.out_src:
            raise ValueError('pipelined top has no `assign out = ...`')


def run_pipeline_netlist(em_in, em_out, sim, pipeline, data: NDArray) -> NDArray[np.float64]:
    """Pack `data`, stream it through the clocked top `sim`, descale.

    Shared by the Verilog and VHDL flavors (the streaming analog of
    ``run_netlist``). Returns floats with the same interpretation as
    ``Pipeline``-replay / ``CombLogic.predict``.
    """
    packed = pack_inputs(em_in.input_layout(), pipeline, data)
    out_bits = sim.run_stream(packed)
    return descale_outputs(em_out.output_layout(), pipeline, out_bits)


def simulate_pipeline(pipeline, name: str = 'sim', data: NDArray | None = None, register_layers: int = 1) -> NDArray[np.float64]:
    """Emit `pipeline` to Verilog and stream `data` through the clocked top."""
    if data is None:  # would otherwise crash deep inside pack_inputs on np.asarray(None)
        raise ValueError('simulate_pipeline requires a (n_samples, n_in) data batch, got None')
    from .comb import VerilogCombEmitter
    from .pipeline import emit_pipeline

    top, mem_files, stage_texts = emit_pipeline(pipeline, name, register_layers=register_layers)
    sim = VerilogPipelineSim(top, stage_texts, mem_files)
    em_in = VerilogCombEmitter(pipeline.stages[0], f'{name}_s0')
    em_out = VerilogCombEmitter(pipeline.stages[-1], f'{name}_s{len(pipeline.stages) - 1}')
    return run_pipeline_netlist(em_in, em_out, sim, pipeline, data)


def simulate_comb(comb, name: str = 'sim', data: NDArray | None = None) -> NDArray[np.float64]:
    """Emit `comb` to Verilog, simulate the netlist over `data`, return floats."""
    if data is None:  # would otherwise crash deep inside pack_inputs on np.asarray(None)
        raise ValueError('simulate_comb requires a (n_samples, n_in) data batch, got None')
    from .comb import VerilogCombEmitter

    em = VerilogCombEmitter(comb, name)
    sim = VerilogNetlistSim(em.emit(), em.mem_files)
    return run_netlist(em, sim, comb, data)
