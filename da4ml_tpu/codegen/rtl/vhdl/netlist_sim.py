"""Bit-exact netlist simulator front-end for the emitted VHDL subset.

Parses VHDLCombEmitter output (signal declarations, concurrent assignments,
entity instantiations) into the same internal structures as the Verilog
netlist simulator and reuses its primitive evaluation engine, providing a
generated-VHDL oracle on hosts without GHDL.
"""

from __future__ import annotations

import re

import numpy as np
from numpy.typing import NDArray

from ..verilog.netlist_sim import PipelineNetlistSim, VerilogNetlistSim, _Instance, _mask, _sext, _shr

_RE_SIG = re.compile(r'signal\s+(\w+)\s*:\s*(std_logic_vector|signed|unsigned)\((\d+)\s+downto\s+0\);')
_RE_ASSIGN = re.compile(r'(\w+)(?:\((\d+)\s+downto\s+(\d+)\))?\s*<=\s*(.+?);')
_RE_INST = re.compile(r'\w+\s*:\s*entity\s+work\.(\w+)\s+generic map\s*\((.*?)\)\s*port map\s*\((.*?)\);')
_RE_KV = re.compile(r'(\w+)\s*=>\s*("[^"]*"|[-\w]+)')

# generic-name aliases between the VHDL and Verilog primitive libraries
_PARAM_ALIASES = {'SUB_OP': 'SUB', 'SHIFT_N': 'SHIFT'}


class VHDLNetlistSim(VerilogNetlistSim):
    def __init__(self, text: str, mem_files: dict[str, str]):
        # bypass the Verilog parser: build structures directly
        self.wire_width = {}
        self.wire_signed = {}
        self.exprs = []
        self.instances = []
        self.mem = {}
        for fname, content in mem_files.items():
            entries: list[int | None] = []
            for line in content.strip().splitlines():
                line = line.strip()
                entries.append(None if 'x' in line else int(line, 16))
            self.mem[fname] = entries

        # a regex miss here would silently mask all I/O to zero width —
        # refuse to simulate unparsed ports, like every other construct
        m = re.search(r'inp : in std_logic_vector\((\d+) downto 0\)', text)
        if not m:
            raise ValueError('Unparsed entity ports: no `inp : in std_logic_vector(hi downto 0)` found')
        self.in_width = int(m.group(1)) + 1
        m = re.search(r'out_port : out std_logic_vector\((\d+) downto 0\)', text)
        if not m:
            raise ValueError('Unparsed entity ports: no `out_port : out std_logic_vector(hi downto 0)` found')
        self.out_width = int(m.group(1)) + 1

        body = text[text.index('architecture') :]
        for raw in body.splitlines():
            line = raw.split('--')[0].strip()
            if not line or line in ('begin', 'end architecture;'):
                continue
            ms = _RE_SIG.match(line)
            if ms:
                name, kind, hi = ms.group(1), ms.group(2), int(ms.group(3))
                self.wire_width[name] = hi + 1
                self.wire_signed[name] = kind == 'signed'
                continue
            mi = _RE_INST.match(line)
            if mi:
                prim, generics_s, ports_s = mi.groups()
                params: dict[str, int | str] = {}
                for k, v in _RE_KV.findall(generics_s):
                    k = _PARAM_ALIASES.get(k, k)
                    params[k] = v.strip('"') if v.startswith('"') else int(v)
                ports = {k: v for k, v in _RE_KV.findall(ports_s)}
                self.instances.append(_Instance(prim, params, ports))
                continue
            ma = _RE_ASSIGN.match(line)
            if ma:
                lhs, hi, lo, rhs = ma.groups()
                if lhs == 'out_port':
                    lhs = 'out'
                sl = (int(hi), int(lo)) if hi is not None else None
                self.exprs.append((lhs, sl, rhs.strip()))
                continue
            if line.startswith(('library', 'use', 'entity', 'port', 'inp :', 'out_port :', ');', 'end entity;', 'architecture')):
                continue
            raise ValueError(f'Unparsed VHDL line: {line}')

    # ----------------------------------------------------------- expression

    def _eval_rhs(self, rhs: str, env: dict[str, int]) -> int:
        rhs = rhs.strip()
        m = re.fullmatch(r'(\w+)\((\d+)\s+downto\s+(\d+)\)', rhs)
        if m:
            name, hi, lo = m.group(1), int(m.group(2)), int(m.group(3))
            return (env[name] >> lo) & _mask(hi - lo + 1)
        m = re.fullmatch(r'"([01]+)"', rhs)
        if m:
            return int(m.group(1), 2)
        if rhs == "(others => '0')":
            return 0
        m = re.fullmatch(r'resize\(signed\((\w+)\), (\d+)\)', rhs)
        if m:
            return _sext(env[m.group(1)], self.wire_width[m.group(1)])
        m = re.fullmatch(r'signed\(resize\(unsigned\((\w+)\), (\d+)\)\)', rhs)
        if m:
            return env[m.group(1)] & _mask(self.wire_width[m.group(1)])
        m = re.fullmatch(r"shift_right\(shift_left\((\w+), (\d+)\), (\d+)\) \+ signed'\(\"([01]+)\"\)", rhs)
        if m:
            base = self._signed_value(m.group(1))
            shifted = _shr(base << int(m.group(2)), int(m.group(3)))
            return shifted + _sext(int(m.group(4), 2), len(m.group(4)))
        m = re.fullmatch(r'std_logic_vector\((\w+)\((\d+)\s+downto\s+(\d+)\)\)', rhs)
        if m:
            name, hi, lo = m.group(1), int(m.group(2)), int(m.group(3))
            return (env[name] >> lo) & _mask(hi - lo + 1)
        if re.fullmatch(r'\w+', rhs):
            return env[rhs]
        raise ValueError(f'Unparsed VHDL rhs: {rhs}')


def simulate_comb_vhdl(comb, name: str = 'sim', data: NDArray | None = None) -> NDArray[np.float64]:
    """Emit `comb` to VHDL, simulate the netlist over `data`, return floats."""
    if data is None:  # would otherwise crash deep inside pack_inputs on np.asarray(None)
        raise ValueError('simulate_comb_vhdl requires a (n_samples, n_in) data batch, got None')
    from ..verilog.netlist_sim import run_netlist
    from .comb import VHDLCombEmitter

    em = VHDLCombEmitter(comb, name)
    sim = VHDLNetlistSim(em.emit(), em.mem_files)
    return run_netlist(em, sim, comb, data)


_RE_VTOP_SIG = re.compile(r'signal\s+(\w+)\s*:\s*std_logic_vector\((\d+)\s+downto\s+0\);')
_RE_VTOP_INST = re.compile(r'\w+\s*:\s*entity\s+work\.(\w+)\s+port map\s*\(inp\s*=>\s*(\w+),\s*out_port\s*=>\s*(\w+)\);')
_RE_VTOP_FF = re.compile(r'process\s*\(clk\)\s*begin\s*if\s*rising_edge\(clk\)\s*then\s*(\w+)\s*<=\s*(\w+);\s*end if;\s*end process;')
_RE_VTOP_OUT = re.compile(r'out_port\s*<=\s*(\w+);')


class VHDLPipelineSim(PipelineNetlistSim):
    """Parse + simulate the VHDL pipelined top emitted by emit_pipeline_vhdl."""

    def __init__(self, top_text: str, stage_texts: list[str], mem_files: dict[str, str]):
        stage_sims: dict[str, VHDLNetlistSim] = {}
        for t in stage_texts:
            ename = re.search(r'entity\s+(\w+)\s+is', t).group(1)
            stage_sims[ename] = VHDLNetlistSim(t, mem_files)

        self.aliases, self.insts, self.regs = [], [], {}
        self.out_src = ''
        # a miss here used to fall back to width 0, masking all I/O to zero;
        # unparsed ports must fail loudly like unparsed body lines
        m = re.search(r'inp : in std_logic_vector\((\d+) downto 0\)', top_text)
        if not m:
            raise ValueError('Unparsed VHDL top ports: no `inp : in std_logic_vector(hi downto 0)` found')
        self.in_width = int(m.group(1)) + 1
        m = re.search(r'out_port : out std_logic_vector\((\d+) downto 0\)', top_text)
        if not m:
            raise ValueError('Unparsed VHDL top ports: no `out_port : out std_logic_vector(hi downto 0)` found')
        self.out_width = int(m.group(1)) + 1

        body = top_text[top_text.index('architecture') :]
        for raw in body.splitlines():
            line = raw.split('--')[0].strip()
            if not line or line in ('begin', 'end architecture;') or line.startswith('architecture'):
                continue
            if m := _RE_VTOP_SIG.match(line):
                pass  # width declaration only
            elif m := _RE_VTOP_FF.match(line):
                self.regs[m.group(1)] = m.group(2)
            elif m := _RE_VTOP_INST.match(line):
                self.insts.append((stage_sims[m.group(1)], m.group(2), m.group(3)))
            elif m := _RE_VTOP_OUT.match(line):
                self.out_src = m.group(1)
            else:
                raise ValueError(f'Unparsed VHDL top line: {line}')
        if not self.out_src:
            raise ValueError('pipelined top has no `out_port <= ...`')


def simulate_pipeline_vhdl(pipeline, name: str = 'sim', data: NDArray | None = None, register_layers: int = 1) -> NDArray[np.float64]:
    """Emit `pipeline` to VHDL and stream `data` through the clocked top."""
    if data is None:  # would otherwise crash deep inside pack_inputs on np.asarray(None)
        raise ValueError('simulate_pipeline_vhdl requires a (n_samples, n_in) data batch, got None')
    from ..verilog.netlist_sim import run_pipeline_netlist
    from .comb import VHDLCombEmitter
    from .pipeline import emit_pipeline_vhdl

    top, mem_files, stage_texts = emit_pipeline_vhdl(pipeline, name, register_layers=register_layers)
    sim = VHDLPipelineSim(top, stage_texts, mem_files)
    em_in = VHDLCombEmitter(pipeline.stages[0], f'{name}_s0')
    em_out = VHDLCombEmitter(pipeline.stages[-1], f'{name}_s{len(pipeline.stages) - 1}')
    return run_pipeline_netlist(em_in, em_out, sim, pipeline, data)
