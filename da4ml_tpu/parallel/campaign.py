"""Pod-scale solve campaigns: shared-filesystem work queue + work stealing.

One campaign = one directory on a filesystem every worker can reach::

    <campaign>/manifest.json        corpus definition (written once, O_EXCL)
    <campaign>/kernels/<key>.json   kernel bytes, one file per unique kernel
    <campaign>/leases/<key>.lease   live claims (reliability.lease)
    <campaign>/results/<key>.json   finished solves, atomic + durable
    <campaign>/failures/<key>.<n>   bounded cross-fleet retry accounting
    <campaign>/workers/<owner>.json worker heartbeats (epoch seconds)

Workers are plain processes — ``run_campaign`` spawns them locally,
``participate`` joins the calling process (e.g. one call per
``jax.distributed`` rank against a shared NFS/GCS-fuse dir). There is no
coordinator: a worker loops *claim an unfinished kernel → solve → write
result → release*, and every step is crash-safe:

- a kernel is **claimed** through a lease file with a deadline; a worker
  renews at ``ttl/3`` while solving, so a SIGKILL at any instruction lets
  the lease expire and a survivor **steal** the kernel
  (``campaign.kernels_stolen``);
- a **result** is one per-kernel file written tmp+fsync+rename+dirfsync
  (:func:`~..reliability.checkpoint.atomic_write_bytes`) — it either exists
  completely or not at all, so a restart resumes byte-identically;
- the corpus is **content-addressed** (:func:`~..reliability.checkpoint.kernel_key`
  over kernel bytes + solver options): resume validates the manifest and
  duplicate kernels collapse onto one solve.

Determinism: within one backend a solve is a pure function of the kernel
and options, so per-kernel results — and therefore the whole campaign — are
byte-identical no matter how kernels are partitioned, stolen, or resumed.
The chaos drill (:func:`chaos_drill`, CI job ``campaign-chaos``) asserts
exactly that with a real mid-solve SIGKILL. Precedent: TVM's decoupled
task-distribution model for autotuning campaigns (arxiv 1802.04799) and the
search campaigns of arxiv 1805.08166.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import threading
import zlib
from pathlib import Path

import numpy as np

from .. import telemetry
from ..reliability.checkpoint import atomic_write_bytes, exclusive_create, kernel_key
from ..reliability.faults import fault_check
from ..reliability.lease import (
    DEFAULT_GRACE_S,
    claim_lease,
    default_owner,
    list_leases,
    release_lease,
    renew_lease,
)
from ..reliability.report import SolveReport

_VERSION = 1

#: a key is declared failed after this many distinct solve failures
#: across the whole fleet (each is a full fallback-chain walk already)
DEFAULT_MAX_FAILURES = 3

#: campaign dir currently driven by this process (health endpoint reads it)
_ACTIVE_DIR: str | None = None


class CampaignError(RuntimeError):
    """A campaign could not complete: corpus mismatch on resume, kernels
    failed on every backend fleet-wide, or workers died without survivors."""


# --------------------------------------------------------------- layout


def _dirs(campaign_dir: str | os.PathLike) -> dict[str, Path]:
    root = Path(campaign_dir)
    return {
        'root': root,
        'kernels': root / 'kernels',
        'leases': root / 'leases',
        'results': root / 'results',
        'failures': root / 'failures',
        'workers': root / 'workers',
        'traces': root / 'traces',
    }


def _jsonable_options(solver_options: dict | None) -> dict:
    opts = dict(solver_options or {})
    if opts.get('qintervals'):
        opts['qintervals'] = [list(t) for t in opts['qintervals']]
    if 'quality' in opts:
        # canonical dict form (a SearchSpec is not JSON-serializable; the
        # fast default drops out so pre-existing manifests keep their keys)
        from ..cmvm.search.spec import quality_key

        qk = quality_key(opts['quality'])
        if qk is None:
            opts.pop('quality')
        else:
            opts['quality'] = qk
    return opts


def create_campaign(
    campaign_dir: str | os.PathLike,
    kernels,
    solver_options: dict | None = None,
    backend: str = 'auto',
    fallback=None,
    resume: bool = False,
) -> dict:
    """Lay out (or rejoin) a campaign directory; returns the manifest.

    The manifest is written through the O_EXCL gate, so any number of
    processes may call this concurrently with the same corpus — one writes,
    the rest validate. A corpus/options mismatch against an existing
    manifest raises :class:`CampaignError` unless the directory is fresh;
    ``resume=False`` additionally refuses a manifest with results already
    present (guards against accidentally extending the wrong directory).
    """
    d = _dirs(campaign_dir)
    for p in d.values():
        p.mkdir(parents=True, exist_ok=True)
    opts = _jsonable_options(solver_options)
    kernels = [np.asarray(k, dtype=np.float64) for k in kernels]
    id_opts = {'solver_options': opts, 'backend': backend}
    key_per_kernel = [kernel_key(k, id_opts) for k in kernels]
    keys = list(dict.fromkeys(key_per_kernel))  # unique work queue, order kept
    manifest = {
        'version': _VERSION,
        'backend': backend,
        'fallback': fallback,
        'solver_options': opts,
        'n_kernels': len(kernels),
        'keys': keys,
        'key_per_kernel': key_per_kernel,
    }
    payload = json.dumps(manifest, sort_keys=True)
    man_path = d['root'] / 'manifest.json'
    if not exclusive_create(man_path, payload.encode()):
        existing = json.loads(man_path.read_text())
        if {k: existing.get(k) for k in ('keys', 'solver_options', 'backend')} != {
            'keys': keys,
            'solver_options': opts,
            'backend': backend,
        }:
            raise CampaignError(
                f'campaign dir {campaign_dir} holds a different corpus/options manifest; '
                f'use a fresh directory or pass the original corpus to resume'
            )
        if not resume and any(d['results'].glob('*.json')):
            raise CampaignError(f'campaign dir {campaign_dir} has prior results; pass resume=True to continue it')
        manifest = existing
    for key, kern in zip(key_per_kernel, kernels):
        path = d['kernels'] / f'{key}.json'
        if not path.exists():
            atomic_write_bytes(path, json.dumps({'key': key, 'kernel': kern.tolist()}).encode())
    return manifest


def load_manifest(campaign_dir: str | os.PathLike) -> dict:
    return json.loads((Path(campaign_dir) / 'manifest.json').read_text())


def _load_kernel(campaign_dir: str | os.PathLike, key: str) -> np.ndarray:
    doc = json.loads((_dirs(campaign_dir)['kernels'] / f'{key}.json').read_text())
    return np.asarray(doc['kernel'], dtype=np.float64)


def _read_result(results_dir: Path, key: str) -> dict | None:
    try:
        return json.loads((results_dir / f'{key}.json').read_text())
    except (OSError, ValueError):
        return None


def _done_keys(results_dir: Path) -> set[str]:
    try:
        return {p.name[:-5] for p in results_dir.glob('*.json')}
    except OSError:
        return set()


# --------------------------------------------------------------- heartbeats


def _safe_owner(owner: str) -> str:
    return owner.replace(os.sep, '_')


def _beat_worker(workers_dir: Path, owner: str, done: int) -> None:
    """Cross-process liveness: one atomically-rewritten file per worker
    carrying a wall-clock stamp, plus the in-process telemetry beat that
    feeds this process's own ``/healthz``."""
    doc = {'owner': owner, 'pid': os.getpid(), 'ts': round(time.time(), 3), 'done': done}
    atomic_write_bytes(workers_dir / f'{_safe_owner(owner)}.json', json.dumps(doc).encode())
    telemetry.beat('campaign')
    telemetry.gauge('campaign.heartbeat_age_s').set(0.0)


def _workers_seen(workers_dir: Path) -> dict[str, dict]:
    out: dict[str, dict] = {}
    try:
        entries = sorted(workers_dir.glob('*.json'))
    except OSError:
        return out
    now = time.time()
    for p in entries:
        try:
            doc = json.loads(p.read_text())
            doc['age_s'] = round(now - float(doc.get('ts', 0.0)), 3)
            out[doc.get('owner', p.stem)] = doc
        except (OSError, ValueError):
            continue
    return out


def campaign_status(campaign_dir: str | os.PathLike, stall_s: float = 60.0) -> dict:
    """Live view of a campaign directory (any process, scrape-safe)."""
    d = _dirs(campaign_dir)
    try:
        n_total = len(load_manifest(campaign_dir)['keys'])
    except (OSError, ValueError, KeyError):
        n_total = None
    done = len(_done_keys(d['results']))
    workers = _workers_seen(d['workers'])
    stalled = sorted(o for o, w in workers.items() if w['age_s'] > stall_s)
    in_progress = n_total is not None and done < n_total
    return {
        'dir': str(d['root']),
        'done': done,
        'total': n_total,
        'in_progress': in_progress,
        'workers_alive': len(workers) - len(stalled),
        'workers': {o: {'age_s': w['age_s'], 'done': w.get('done')} for o, w in workers.items()},
        'stalled': stalled,
        'leases': len(list_leases(d['leases'])),
    }


def worker_health(stall_s: float = 60.0) -> dict | None:
    """Campaign worker liveness for ``/healthz`` (None outside a campaign).
    Read via ``sys.modules`` by ``telemetry.obs.health`` so a scrape never
    imports this module."""
    if _ACTIVE_DIR is None:
        return None
    try:
        return campaign_status(_ACTIVE_DIR, stall_s=stall_s)
    except OSError:  # pragma: no cover - campaign dir vanished mid-scrape
        return None


# --------------------------------------------------------------- worker


class _Renewer(threading.Thread):
    """Renews one held lease at ttl/3 cadence until stopped (daemon: dies
    with the process, which is exactly what lets survivors steal)."""

    def __init__(self, lease, interval_s: float):
        super().__init__(name=f'da4ml-lease-renew-{lease.key[:8]}', daemon=True)
        self.lease = lease
        self.interval_s = interval_s
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.wait(self.interval_s):
            if not renew_lease(self.lease):
                return  # stolen out from under us; solve result stays idempotent

    def stop(self) -> None:
        self._stop.set()


def _record_failure(d: dict[str, Path], key: str, owner: str, exc: BaseException, max_failures: int) -> int:
    """Bounded fleet-wide retry: one O_EXCL marker per failure. Returns the
    failure count; at ``max_failures`` a terminal failed-result doc is
    written so the campaign completes instead of ping-ponging forever."""
    doc = json.dumps({'key': key, 'owner': owner, 'error': f'{type(exc).__name__}: {exc}'[:300]}).encode()
    for n in range(max_failures):
        if exclusive_create(d['failures'] / f'{key}.{n}.json', doc):
            count = n + 1
            break
    else:
        count = max_failures
    if count >= max_failures and not (d['results'] / f'{key}.json').exists():
        atomic_write_bytes(
            d['results'] / f'{key}.json',
            json.dumps(
                {'version': _VERSION, 'key': key, 'failed': True, 'error': f'{type(exc).__name__}: {exc}'[:300]}
            ).encode(),
        )
    return count


def worker_loop(
    campaign_dir: str | os.PathLike,
    owner: str | None = None,
    ttl_s: float = 30.0,
    poll_s: float = 0.5,
    grace_s: float | None = None,
    deadline_per_solve: float | None = None,
    max_kernels: int | None = None,
    max_failures: int = DEFAULT_MAX_FAILURES,
    store=None,
) -> dict:
    """Drive one worker until the campaign is complete; returns a summary
    ``{'owner', 'solved', 'stolen', 'duration_s', ...}``.

    Safe to run in any number of processes against the same directory.
    ``max_kernels`` bounds this worker's own contribution (tests; draining
    a worker before maintenance). ``store`` (or ``DA4ML_SOLUTION_STORE``)
    names a global solution store (docs/store.md) to publish finished
    solves into, so campaign output warms every future ``solve()``.
    """
    global _ACTIVE_DIR
    from ..reliability.orchestrator import canonical_backend, solve_orchestrated
    from ..store.solution_store import resolve_store, store_key

    d = _dirs(campaign_dir)
    manifest = load_manifest(campaign_dir)
    solution_store = resolve_store(store)
    store_backend = canonical_backend(manifest['backend'])
    keys: list[str] = list(manifest['keys'])
    owner = owner or default_owner('w')
    grace = grace_s if grace_s is not None else max(DEFAULT_GRACE_S, ttl_s / 3)
    # rotate the scan order per owner so a fleet doesn't hammer key 0
    i0 = zlib.crc32(owner.encode()) % max(1, len(keys))
    order = keys[i0:] + keys[:i0]

    _ACTIVE_DIR = str(d['root'])
    solved: list[str] = []
    stolen = 0
    report = SolveReport()
    t0 = time.monotonic()
    telemetry.gauge('campaign.total').set(len(keys))
    with telemetry.span('campaign.worker', owner=owner, n_kernels=len(keys)):
        while True:
            done = _done_keys(d['results'])
            _beat_worker(d['workers'], owner, len(done))
            telemetry.gauge('campaign.done').set(len(done))
            telemetry.gauge('campaign.workers_alive').set(campaign_status(campaign_dir, stall_s=3 * ttl_s)['workers_alive'])
            missing = [k for k in order if k not in done]
            if not missing or (max_kernels is not None and len(solved) >= max_kernels):
                break
            lease = None
            for key in missing:
                lease = claim_lease(d['leases'], key, owner=owner, ttl_s=ttl_s, grace_s=grace)
                if lease is not None:
                    break
            if lease is None:
                # everything unfinished is live-leased by someone else:
                # wait for results to land or leases to expire
                time.sleep(poll_s)
                continue
            telemetry.counter('campaign.claims').inc()
            if lease.stolen_from:
                stolen += 1
                telemetry.counter('campaign.kernels_stolen').inc()
                telemetry.instant('campaign.steal', key=lease.key, owner=owner, stolen_from=lease.stolen_from)
            renewer = _Renewer(lease, interval_s=ttl_s / 3.0)
            renewer.start()
            try:
                # chaos-drill site: a planned sleep here parks the worker
                # mid-solve with the lease held (renewed by the daemon
                # thread), the exact state a SIGKILL must recover from
                fault_check('campaign.solve')
                t_k = time.monotonic()
                kern = _load_kernel(campaign_dir, lease.key)
                with telemetry.span('campaign.kernel', key=lease.key, owner=owner):
                    try:
                        pipe = solve_orchestrated(
                            kern,
                            dict(manifest['solver_options']),
                            backend=manifest['backend'],
                            fallback=manifest.get('fallback'),
                            deadline=deadline_per_solve,
                            report=report,
                        )
                    except Exception as exc:
                        n_fail = _record_failure(d, lease.key, owner, exc, max_failures)
                        telemetry.counter('campaign.kernel_failures').inc()
                        telemetry.instant('campaign.kernel_failed', key=lease.key, n=n_fail, error=type(exc).__name__)
                        continue
                doc = {
                    'version': _VERSION,
                    'key': lease.key,
                    'cost': float(pipe.cost),
                    'backend': report.backend_used,
                    'owner': owner,
                    'stolen_from': lease.stolen_from,
                    'duration_s': round(time.monotonic() - t_k, 6),
                    'pipeline': pipe.to_dict(),
                }
                atomic_write_bytes(d['results'] / f'{lease.key}.json', json.dumps(doc).encode())
                solved.append(lease.key)
                # publish into the shared solution store so future solve()
                # calls anywhere on the fleet start warm — only results the
                # manifest's own backend produced (a fallback-degraded
                # answer must not poison the requested-backend key)
                if solution_store is not None and report.backend_used in (None, store_backend):
                    solution_store.publish(
                        store_key(kern, manifest['backend'], dict(manifest['solver_options'])),
                        pipe,
                        meta={'backend': store_backend, 'campaign': str(d['root']), 'owner': owner},
                    )
                # kill-after-durable-result drill point (mirrors
                # checkpoint.post_save): the result above survives this
                fault_check('campaign.post_result')
            finally:
                renewer.stop()
                release_lease(lease)
    done = _done_keys(d['results'])
    _beat_worker(d['workers'], owner, len(done))
    telemetry.gauge('campaign.done').set(len(done))
    return {
        'owner': owner,
        'solved': solved,
        'n_solved': len(solved),
        'stolen': stolen,
        'checkpoint_hits': len(keys) - len(solved),
        'duration_s': round(time.monotonic() - t0, 6),
        'complete': len(done) >= len(keys),
    }


def participate(
    campaign_dir: str | os.PathLike,
    kernels,
    solver_options: dict | None = None,
    backend: str = 'auto',
    **worker_kw,
) -> tuple[list, dict]:
    """Join the calling process to a shared campaign: ensure the manifest
    (O_EXCL; all participants must pass the same corpus), run a worker to
    completion, and collect. This is the one call per ``jax.distributed``
    rank — the work queue partitions dynamically over however many ranks
    show up, and survivors absorb dead ranks' kernels."""
    create_campaign(campaign_dir, kernels, solver_options, backend=backend, resume=True)
    summary = worker_loop(campaign_dir, **worker_kw)
    return collect_results(campaign_dir), summary


# --------------------------------------------------------------- collect


def collect_results(campaign_dir: str | os.PathLike, allow_failed: bool = False) -> list[dict]:
    """Result docs in original corpus order (duplicates fan back out).

    Raises :class:`CampaignError` on missing results (campaign still in
    flight / workers all died) or terminally-failed kernels (unless
    ``allow_failed``). Every doc carries ``key``/``cost``/``backend``/
    ``owner``/``pipeline`` — byte-stable per key regardless of which worker
    produced it.
    """
    d = _dirs(campaign_dir)
    manifest = load_manifest(campaign_dir)
    out, missing, failed = [], [], []
    for key in manifest['key_per_kernel']:
        doc = _read_result(d['results'], key)
        if doc is None:
            missing.append(key)
        elif doc.get('failed'):
            failed.append(key)
            out.append(doc)
        else:
            out.append(doc)
    if missing:
        raise CampaignError(f'campaign incomplete: {len(missing)}/{len(manifest["key_per_kernel"])} results missing')
    if failed and not allow_failed:
        raise CampaignError(f'{len(failed)} kernels failed on every backend fleet-wide: {failed[:4]}')
    return out


def results_to_pipelines(results: list[dict]):
    from ..ir.comb import Pipeline

    return [Pipeline.from_dict(doc['pipeline']) for doc in results]


# --------------------------------------------------------------- driver


def _repo_pythonpath(env: dict) -> dict:
    """Child processes must resolve the same da4ml_tpu this parent runs."""
    pkg_root = str(Path(__file__).resolve().parents[2])
    env['PYTHONPATH'] = pkg_root + os.pathsep + env.get('PYTHONPATH', '') if env.get('PYTHONPATH') else pkg_root
    return env


def _spawn_worker(
    campaign_dir: str | os.PathLike,
    owner: str,
    ttl_s: float,
    poll_s: float,
    deadline_per_solve: float | None,
    env: dict | None = None,
    trace: bool = False,
    store: str | None = None,
) -> subprocess.Popen:
    cmd = [
        sys.executable,
        '-m',
        'da4ml_tpu.parallel.campaign',
        '--worker',
        str(campaign_dir),
        '--owner',
        owner,
        '--ttl',
        str(ttl_s),
        '--poll',
        str(poll_s),
    ]
    if deadline_per_solve is not None:
        cmd += ['--deadline', str(deadline_per_solve)]
    if store is not None:
        cmd += ['--store', str(store)]
    env = _repo_pythonpath(dict(os.environ if env is None else env))
    # children never inherit the parent's trace file or metrics port: N
    # workers appending one trace (or binding one port) corrupts both.
    # Worker tracing is opt-in and lands per-owner under <campaign>/traces/.
    env.pop('DA4ML_METRICS_PORT', None)
    if trace:
        env['DA4ML_TRACE'] = str(_dirs(campaign_dir)['traces'] / f'{_safe_owner(owner)}.jsonl')
    else:
        env.pop('DA4ML_TRACE', None)
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _last_json_line(text: str) -> dict | None:
    for line in reversed((text or '').strip().splitlines()):
        if line.startswith('{'):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def run_campaign(
    kernels,
    workers: int = 3,
    campaign_dir: str | os.PathLike | None = None,
    solver_options: dict | None = None,
    backend: str = 'auto',
    fallback=None,
    resume: bool = True,
    ttl_s: float = 30.0,
    poll_s: float = 0.5,
    deadline_per_solve: float | None = None,
    timeout_s: float = 3600.0,
    trace: bool = False,
    store: str | os.PathLike | None = None,
) -> tuple[list[dict], dict]:
    """Solve a corpus with ``workers`` local processes; returns
    ``(result docs in corpus order, campaign report)``.

    ``store`` names a global solution-store directory (docs/store.md) every
    worker publishes finished solves into; with no argument, workers still
    pick one up from ``DA4ML_SOLUTION_STORE`` in their environment.

    ``workers <= 1`` runs in-process (the single-process reference the chaos
    drill compares against). A worker crash mid-campaign is absorbed: its
    leases expire and survivors steal the kernels; only losing *every*
    worker raises (and even then the directory resumes where it stopped).
    """
    global _ACTIVE_DIR
    if campaign_dir is None:
        import tempfile

        campaign_dir = tempfile.mkdtemp(prefix='da4ml-campaign-')
    create_campaign(campaign_dir, kernels, solver_options, backend=backend, fallback=fallback, resume=resume)
    t0 = time.monotonic()
    report: dict = {'dir': str(campaign_dir), 'workers': workers}
    with telemetry.span('campaign.run', n_kernels=len(load_manifest(campaign_dir)['keys']), workers=workers):
        if workers <= 1:
            summary = worker_loop(
                campaign_dir, ttl_s=ttl_s, poll_s=poll_s, deadline_per_solve=deadline_per_solve, store=store
            )
            report['worker_summaries'] = [summary]
        else:
            _ACTIVE_DIR = str(campaign_dir)
            procs = [
                _spawn_worker(
                    campaign_dir,
                    f'{default_owner()}:w{i}',
                    ttl_s,
                    poll_s,
                    deadline_per_solve,
                    trace=trace,
                    store=None if store is None else str(store),
                )
                for i in range(workers)
            ]
            summaries, failures = [], []
            deadline = time.monotonic() + timeout_s
            try:
                for p in procs:
                    try:
                        out, err = p.communicate(timeout=max(1.0, deadline - time.monotonic()))
                    except subprocess.TimeoutExpired:
                        p.kill()
                        out, err = p.communicate()
                        failures.append({'pid': p.pid, 'rc': 'timeout'})
                        continue
                    summary = _last_json_line(out)
                    if p.returncode == 0 and summary is not None:
                        summaries.append(summary)
                    else:
                        failures.append(
                            {'pid': p.pid, 'rc': p.returncode, 'stderr': (err or '').strip()[-300:]}
                        )
            finally:
                for p in procs:
                    if p.poll() is None:  # pragma: no cover - timeout cleanup
                        p.kill()
            report['worker_summaries'] = summaries
            if failures:
                report['worker_failures'] = failures
            if not summaries and failures:
                raise CampaignError(f'every campaign worker died: {failures}')
    results = collect_results(campaign_dir)
    report['n_kernels'] = len(results)
    report['kernels_stolen'] = sum(s.get('stolen', 0) for s in report['worker_summaries'])
    report['wall_s'] = round(time.monotonic() - t0, 6)
    report['costs'] = [doc.get('cost') for doc in results]
    telemetry.instant('campaign.complete', **{k: report[k] for k in ('n_kernels', 'kernels_stolen', 'wall_s')})
    return results, report


# --------------------------------------------------------------- chaos drill


def _drill_corpus(n: int = 6, dim: int = 8, bits: int = 3) -> list[np.ndarray]:
    rng = np.random.default_rng(20260804)
    return [
        (rng.integers(0, 2**bits, (dim, dim)) * rng.choice([-1.0, 1.0], (dim, dim))).astype(np.float64)
        for _ in range(n)
    ]


def chaos_drill(
    kernels=None,
    workers: int = 3,
    base_dir: str | os.PathLike | None = None,
    backend: str = 'pure-python',
    solver_options: dict | None = None,
    ttl_s: float = 2.0,
    poll_s: float = 0.2,
    victim_stall_s: float = 120.0,
    timeout_s: float = 420.0,
    trace: bool = False,
) -> dict:
    """Deterministic kill-a-worker drill; returns a report with ``ok``.

    Sequence: (1) solve the corpus single-process — the byte-identity
    reference; (2) start ``workers`` subprocess workers on a fresh campaign
    dir, with worker 0 (the victim) fault-injected to park mid-solve
    (``campaign.solve=sleep``) while its lease renews; (3) wait until the
    victim provably holds a lease, then SIGKILL it; (4) survivors steal the
    victim's kernel after lease expiry and finish the corpus. Passes iff the
    corpus completed, at least one kernel was stolen, nothing was lost or
    double-reported, and every per-kernel result is byte-identical to the
    single-process reference.
    """
    import tempfile

    kernels = _drill_corpus() if kernels is None else list(kernels)
    base = Path(base_dir) if base_dir is not None else Path(tempfile.mkdtemp(prefix='da4ml-chaos-'))
    report: dict = {'base_dir': str(base), 'workers': workers, 'n_kernels': len(kernels)}

    # (1) single-process reference
    ref_results, ref_report = run_campaign(
        kernels, workers=1, campaign_dir=base / 'reference', solver_options=solver_options, backend=backend
    )
    ref_blobs = {doc['key']: json.dumps(doc['pipeline'], sort_keys=True) for doc in ref_results}
    report['reference_wall_s'] = ref_report['wall_s']

    # (2) the drill campaign: victim + survivors
    drill_dir = base / 'drill'
    create_campaign(drill_dir, kernels, solver_options, backend=backend)
    victim_owner = f'{default_owner()}:victim'
    victim_env = dict(os.environ, DA4ML_FAULT_INJECT=f'campaign.solve=sleep:1:{victim_stall_s}')
    victim = _spawn_worker(drill_dir, victim_owner, ttl_s, poll_s, None, env=victim_env, trace=trace)
    survivors = [
        _spawn_worker(drill_dir, f'{default_owner()}:survivor{i}', ttl_s, poll_s, None, trace=trace)
        for i in range(workers - 1)
    ]
    deadline = time.monotonic() + timeout_s
    try:
        # (3) SIGKILL the victim only once it provably holds a lease
        victim_key = None
        while time.monotonic() < deadline and victim_key is None:
            for key, doc in list_leases(_dirs(drill_dir)['leases']).items():
                if doc.get('owner') == victim_owner:
                    victim_key = key
                    break
            if victim_key is None:
                if victim.poll() is not None:
                    raise CampaignError(f'victim exited before claiming a lease: {victim.communicate()[1][-300:]}')
                time.sleep(0.05)
        report['victim_claimed_key'] = victim_key
        if victim_key is None:
            raise CampaignError('victim never claimed a lease within the drill timeout')
        os.kill(victim.pid, signal.SIGKILL)
        victim.communicate()
        report['victim_rc'] = victim.returncode

        # (4) survivors must finish the corpus alone
        summaries = []
        for p in survivors:
            out, err = p.communicate(timeout=max(1.0, deadline - time.monotonic()))
            if p.returncode != 0:
                raise CampaignError(f'survivor rc={p.returncode}: {(err or "")[-300:]}')
            summaries.append(_last_json_line(out) or {})
    finally:
        for p in [victim, *survivors]:
            if p.poll() is None:
                p.kill()

    results = collect_results(drill_dir)
    blobs = {doc['key']: json.dumps(doc['pipeline'], sort_keys=True) for doc in results}
    owners = {doc['key']: doc['owner'] for doc in results}
    report['survivor_summaries'] = summaries
    report['kernels_stolen'] = sum(s.get('stolen', 0) for s in summaries)
    report['victim_kernel_owner'] = owners.get(victim_key)
    report['n_results'] = len(results)
    report['unique_keys'] = len(blobs)
    report['byte_identical'] = blobs == ref_blobs
    report['costs'] = [doc['cost'] for doc in results]
    report['checks'] = {
        'corpus_complete': len(results) == len(kernels) and len(blobs) == len(ref_blobs),
        'byte_identical_to_reference': report['byte_identical'],
        'victim_killed': report['victim_rc'] != 0,
        'kernel_stolen': report['kernels_stolen'] >= 1,
        'victim_kernel_rescued': owners.get(victim_key) not in (None, victim_owner),
    }
    report['ok'] = all(report['checks'].values())
    return report


# --------------------------------------------------------------- worker entry


def _worker_main(argv: list[str]) -> int:
    """``python -m da4ml_tpu.parallel.campaign --worker <dir> ...`` — the
    subprocess entry behind ``run_campaign`` / the campaign CLI. Prints one
    JSON summary line (last-line-wins, like bench sections)."""
    import argparse

    ap = argparse.ArgumentParser(prog='da4ml_tpu.parallel.campaign')
    ap.add_argument('--worker', required=True, metavar='DIR')
    ap.add_argument('--owner', default=None)
    ap.add_argument('--ttl', type=float, default=30.0)
    ap.add_argument('--poll', type=float, default=0.5)
    ap.add_argument('--deadline', type=float, default=None)
    ap.add_argument('--max-kernels', type=int, default=None)
    ap.add_argument('--store', default=None, metavar='DIR')
    args = ap.parse_args(argv)
    summary = worker_loop(
        args.worker,
        owner=args.owner,
        ttl_s=args.ttl,
        poll_s=args.poll,
        deadline_per_solve=args.deadline,
        max_kernels=args.max_kernels,
        store=args.store,
    )
    print(json.dumps(summary), flush=True)
    return 0 if summary['complete'] else 3


if __name__ == '__main__':
    sys.exit(_worker_main(sys.argv[1:]))
