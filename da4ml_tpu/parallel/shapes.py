"""Canonical shape grid shared by the CMVM scheduler and the serve batcher.

The PR-4 device scheduler buckets every compiled shape onto a
``2^k / 3·2^k / 5·2^k`` grid so heterogeneous workloads share a small set
of XLA executables and the persistent compile cache turns each class into
a one-time cost per machine (``docs/api.md`` scheduler knobs,
``docs/cmvm.md``). The serving layer reuses the same grid on the *sample*
axis: a coalesced request batch is padded up to the nearest grid rung, so
every batch a warm server dispatches lands on an already-compiled shape
(``docs/serving.md``).

Numpy-only on purpose: importable by both ``cmvm.jax_search`` and
``serve.batching`` without touching jax.
"""

from __future__ import annotations

import numpy as np
from numpy.typing import NDArray


def next_pow2(x: int) -> int:
    """Smallest power of two >= max(x, 1)."""
    return 1 << (max(x, 1) - 1).bit_length()


def canon_dim(x: int, lo: int = 2, even: bool = True) -> int:
    """Round a shape dim up to the canonical 2^k / 3·2^k / 5·2^k grid.

    The grid (…, lo, 4, 6, 8, 10, 12, 16, 20, 24, 32, 40, 48, …) is
    batch-independent: a matrix always lands in the same (O, B) class no
    matter what else is in the batch, so thousands of heterogeneous
    matrices share a small set of compiled executables — and the
    persistent XLA cache makes those classes one-time costs per machine,
    not per process. 3·2^k / 5·2^k rungs halve the worst-case padding
    waste of a pure pow2 grid; the per-iteration search cost scales with
    O·B², so the padding quantum matters.

    ``even=True`` (the CMVM scheduler's setting) keeps odd 3·2^0 / 5·2^0
    rungs off the grid, since B buckets to even counts. The serve batcher
    uses ``even=False, lo=1`` so tiny request batches (1, 2, 3, 5 rows)
    are not padded up to the even grid.
    """
    x = max(x, lo)
    p2 = next_pow2(x)
    best = p2
    for c in ((p2 // 4) * 3, (p2 // 8) * 5):
        if x <= c and c >= lo and (not even or c % 2 == 0) and c < best:
            best = c
    return best


def grid_rungs(max_dim: int, lo: int = 1, even: bool = False) -> list[int]:
    """Every canonical grid value in ``[lo, canon_dim(max_dim)]``, ascending.

    This is the serve warmup ladder: pre-dispatching one batch per rung
    means a warm server never meets a new XLA shape
    (``serve.ServeEngine.warmup``).
    """
    rungs: set[int] = set()
    d = lo
    top = canon_dim(max_dim, lo=lo, even=even)
    while d <= top:
        c = canon_dim(d, lo=lo, even=even)
        rungs.add(c)
        d = c + 1
    return sorted(rungs)


def pad_rows(x: NDArray, lo: int = 1, even: bool = False) -> tuple[NDArray, int]:
    """Pad the sample axis (axis 0) up to the canonical grid with zero rows.

    Returns ``(padded, n)`` where ``n`` is the original row count. Row-wise
    kernels (every DAIS program is one) give bit-identical results on the
    first ``n`` rows — proven through ``DaisExecutor.__call__`` by
    ``tests/test_serve.py``.
    """
    x = np.asarray(x)
    n = x.shape[0]
    target = canon_dim(n, lo=lo, even=even)
    if target == n:
        return x, n
    widths = [(0, target - n)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, widths), n


def canon_multiple(n: int, multiple: int) -> int:
    """Smallest canonical grid rung >= ``n`` divisible by ``multiple``;
    plain round-up when the grid has no such rung (multiples off the
    2^k / 3·2^k / 5·2^k lattice, e.g. 7 devices).

    This is the mesh-aware batch quantum: a batch sharded over ``multiple``
    devices must split evenly, and landing the padded size on the grid
    keeps the dispatch on an already-compiled shape
    (docs/serving.md#shape-canonicalization).
    """
    multiple = max(multiple, 1)
    c = canon_dim(max(n, multiple), lo=1, even=False)
    # rung spacing is geometric (ratio <= 4/3): a divisible rung, if one
    # exists, appears within a few steps of doubling past n
    limit = next_pow2(max(n, multiple)) * 2
    while c <= limit:
        if c % multiple == 0:
            return c
        c = canon_dim(c + 1, lo=1, even=False)
    return -(-n // multiple) * multiple


def pad_rows_multiple(x: NDArray, multiple: int) -> tuple[NDArray, int]:
    """Pad the sample axis up to :func:`canon_multiple`; returns ``(padded, n)``.

    The runtime's sharded dispatch path uses this so small or ragged
    batches still ride the device mesh — padded onto the canonical grid,
    split evenly across devices, trimmed after — instead of silently
    falling back to single-device execution.
    """
    x = np.asarray(x)
    n = x.shape[0]
    target = canon_multiple(n, multiple)
    if target == n:
        return x, n
    widths = [(0, target - n)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, widths), n
