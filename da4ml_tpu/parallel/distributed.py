"""Multi-host initialization: the distributed runtime behind mesh sharding.

The reference's only cross-worker transport is shared-memory OpenMP inside
one process (meson.build:21 / api.cc:208 of calad0i/da4ml); scaling past
one host here means the JAX distributed runtime + XLA collectives over
ICI/DCN instead of a custom NCCL/MPI layer. After ``initialize()``,
``jax.devices()`` spans every process, ``global_mesh()`` builds a mesh over
all of them, and the existing entry points (``solve_jax_many(mesh=...)``,
``DaisExecutor.predict_sharded``) shard their lane/sample axes across hosts
with XLA inserting the collectives — the candidate argmin stays a host-side
reduction over gathered per-lane costs, which is bytes per lane.

Single-host multi-device needs none of this: a `Mesh` over local devices
(``parallel.default_mesh``) is enough, as exercised by the virtual-device
CI mesh.
"""

from __future__ import annotations

import os

#: rendezvous budget defaults; override with DA4ML_DIST_CONNECT_RETRIES /
#: DA4ML_DIST_CONNECT_TIMEOUT_S (docs/distributed.md)
DEFAULT_CONNECT_RETRIES = 3
DEFAULT_CONNECT_TIMEOUT_S = 60.0


def connect_budget() -> tuple[int, float]:
    """(retries, timeout_s) for the coordinator rendezvous, env-overridable.

    ``DA4ML_DIST_CONNECT_RETRIES`` bounds how many times a transient connect
    failure is retried (0 disables retry); ``DA4ML_DIST_CONNECT_TIMEOUT_S``
    bounds each attempt (forwarded to ``jax.distributed.initialize`` as
    ``initialization_timeout`` when the running jax supports it) and shapes
    the backoff ceiling. Bad values fall back to the defaults rather than
    failing a pod bring-up over a typo.
    """
    try:
        retries = int(os.environ.get('DA4ML_DIST_CONNECT_RETRIES', '') or DEFAULT_CONNECT_RETRIES)
    except ValueError:
        retries = DEFAULT_CONNECT_RETRIES
    try:
        timeout_s = float(os.environ.get('DA4ML_DIST_CONNECT_TIMEOUT_S', '') or DEFAULT_CONNECT_TIMEOUT_S)
    except ValueError:
        timeout_s = DEFAULT_CONNECT_TIMEOUT_S
    return max(0, retries), max(1.0, timeout_s)


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> bool:
    """Initialize the JAX distributed runtime (idempotent).

    Arguments default to the standard JAX env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``)
    or managed-cluster auto-detection. Returns True when a multi-process
    runtime is active after the call, False for plain single-process use
    (nothing to do, or no coordinator configured).
    """
    import jax

    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, 'client', None) is not None:
            return jax.process_count() > 1  # already initialized
    except Exception:
        pass  # private-module layout changed; fall through to initialize

    # cross-process collectives on the CPU backend need an explicit
    # transport — without one every cross-host program deadlocks silently.
    # Must be set before the backend initializes; harmless for TPU.
    try:
        if not jax.config.read('jax_cpu_collectives_implementation'):
            jax.config.update('jax_cpu_collectives_implementation', 'gloo')
    except Exception:
        pass  # knob absent in this jax version

    coordinator_address = coordinator_address or os.environ.get('JAX_COORDINATOR_ADDRESS')
    if num_processes is None and os.environ.get('JAX_NUM_PROCESSES'):
        num_processes = int(os.environ['JAX_NUM_PROCESSES'])
    if process_id is None and os.environ.get('JAX_PROCESS_ID'):
        process_id = int(os.environ['JAX_PROCESS_ID'])

    if coordinator_address is None and num_processes is None:
        # No explicit config: let managed clusters (TPU pods, SLURM, ...)
        # auto-detect. A bare single process raises (ValueError for missing
        # config, RuntimeError when JAX already ran computations) — both
        # mean "no cluster here", so report single-host. Failures under
        # *explicit* configuration never take this path and always surface.
        try:
            jax.distributed.initialize(**kwargs)
        except (ValueError, RuntimeError):
            return False
        return jax.process_count() > 1

    # Explicitly configured rendezvous: the coordinator may not be listening
    # yet (worker raced ahead of rank 0, pod still scheduling) — a transient,
    # not a config error. Retry with backoff + jitter before surfacing
    # (each retry sleep lands in the `retry.sleeps` / `retry.delay_s`
    # metrics via retry_call); DA4ML_DIST_CONNECT_RETRIES /
    # DA4ML_DIST_CONNECT_TIMEOUT_S override the budget (connect_budget).
    from ..reliability.faults import fault_check
    from ..reliability.retry import retry_call

    retries, timeout_s = connect_budget()
    if 'initialization_timeout' not in kwargs:
        import inspect

        try:
            if 'initialization_timeout' in inspect.signature(jax.distributed.initialize).parameters:
                kwargs['initialization_timeout'] = int(timeout_s)
        except (TypeError, ValueError):  # pragma: no cover - exotic jax builds
            pass

    def _connect():
        fault_check('distributed.init')
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )

    def _is_connect_flake(exc: BaseException) -> bool:
        from ..reliability.errors import TransientError

        if isinstance(exc, (ConnectionError, TransientError)):
            return True
        msg = str(exc).lower()  # gRPC surfaces as RuntimeError; match the
        return any(m in msg for m in ('connect', 'deadline', 'unavailable', 'timed out'))  # rendezvous flakes only

    # backoff ceiling scales with the per-attempt budget so the whole walk
    # (attempts + sleeps) stays within the same order as the configured
    # timeout instead of a hardcoded 10 s cap
    retry_call(_connect, retries=retries, base_delay=0.5, max_delay=max(1.0, timeout_s / 4.0), retry_on=_is_connect_flake)
    return jax.process_count() > 1


def global_mesh(axis_name: str = 'lanes'):
    """A 1D mesh over every device of every participating process.

    With the distributed runtime active this spans hosts (lane shards ride
    ICI within a slice and DCN across slices, scheduled by XLA); otherwise
    it is just the local-device mesh.
    """
    from . import default_mesh

    return default_mesh(axis_name)
