"""Multi-host initialization: the distributed runtime behind mesh sharding.

The reference's only cross-worker transport is shared-memory OpenMP inside
one process (meson.build:21 / api.cc:208 of calad0i/da4ml); scaling past
one host here means the JAX distributed runtime + XLA collectives over
ICI/DCN instead of a custom NCCL/MPI layer. After ``initialize()``,
``jax.devices()`` spans every process, ``global_mesh()`` builds a mesh over
all of them, and the existing entry points (``solve_jax_many(mesh=...)``,
``DaisExecutor.predict_sharded``) shard their lane/sample axes across hosts
with XLA inserting the collectives — the candidate argmin stays a host-side
reduction over gathered per-lane costs, which is bytes per lane.

Single-host multi-device needs none of this: a `Mesh` over local devices
(``parallel.default_mesh``) is enough, as exercised by the virtual-device
CI mesh.
"""

from __future__ import annotations

import os


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> bool:
    """Initialize the JAX distributed runtime (idempotent).

    Arguments default to the standard JAX env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``)
    or managed-cluster auto-detection. Returns True when a multi-process
    runtime is active after the call, False for plain single-process use
    (nothing to do, or no coordinator configured).
    """
    import jax

    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, 'client', None) is not None:
            return jax.process_count() > 1  # already initialized
    except Exception:
        pass  # private-module layout changed; fall through to initialize

    # cross-process collectives on the CPU backend need an explicit
    # transport — without one every cross-host program deadlocks silently.
    # Must be set before the backend initializes; harmless for TPU.
    try:
        if not jax.config.read('jax_cpu_collectives_implementation'):
            jax.config.update('jax_cpu_collectives_implementation', 'gloo')
    except Exception:
        pass  # knob absent in this jax version

    coordinator_address = coordinator_address or os.environ.get('JAX_COORDINATOR_ADDRESS')
    if num_processes is None and os.environ.get('JAX_NUM_PROCESSES'):
        num_processes = int(os.environ['JAX_NUM_PROCESSES'])
    if process_id is None and os.environ.get('JAX_PROCESS_ID'):
        process_id = int(os.environ['JAX_PROCESS_ID'])

    if coordinator_address is None and num_processes is None:
        # No explicit config: let managed clusters (TPU pods, SLURM, ...)
        # auto-detect. A bare single process raises (ValueError for missing
        # config, RuntimeError when JAX already ran computations) — both
        # mean "no cluster here", so report single-host. Failures under
        # *explicit* configuration never take this path and always surface.
        try:
            jax.distributed.initialize(**kwargs)
        except (ValueError, RuntimeError):
            return False
        return jax.process_count() > 1

    # Explicitly configured rendezvous: the coordinator may not be listening
    # yet (worker raced ahead of rank 0, pod still scheduling) — a transient,
    # not a config error. Retry with backoff + jitter before surfacing;
    # DA4ML_DIST_CONNECT_RETRIES overrides the budget (0 disables).
    from ..reliability.faults import fault_check
    from ..reliability.retry import retry_call

    def _connect():
        fault_check('distributed.init')
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )

    def _is_connect_flake(exc: BaseException) -> bool:
        from ..reliability.errors import TransientError

        if isinstance(exc, (ConnectionError, TransientError)):
            return True
        msg = str(exc).lower()  # gRPC surfaces as RuntimeError; match the
        return any(m in msg for m in ('connect', 'deadline', 'unavailable', 'timed out'))  # rendezvous flakes only

    retries = int(os.environ.get('DA4ML_DIST_CONNECT_RETRIES', '3') or 0)
    retry_call(_connect, retries=retries, base_delay=0.5, max_delay=10.0, retry_on=_is_connect_flake)
    return jax.process_count() > 1


def global_mesh(axis_name: str = 'lanes'):
    """A 1D mesh over every device of every participating process.

    With the distributed runtime active this spans hosts (lane shards ride
    ICI within a slice and DCN across slices, scheduled by XLA); otherwise
    it is just the local-device mesh.
    """
    from . import default_mesh

    return default_mesh(axis_name)
