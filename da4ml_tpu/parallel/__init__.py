"""Device-mesh utilities: batch sharding for inference, candidate sharding for search.

The framework's two parallel axes (SURVEY.md §2.6):
  - DAIS batch inference  -> shard the sample axis over the mesh
  - CMVM candidate search -> shard the (matrix × dc × restart) axis

Both ride XLA collectives over ICI; no custom transport.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def default_mesh(axis_name: str = 'batch', devices=None) -> Mesh:
    """A 1D mesh over all local devices."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devices.reshape(-1), (axis_name,))


def batch_sharding(mesh: Mesh, axis_name: str = 'batch') -> NamedSharding:
    """Shard the leading (sample) axis; everything else replicated."""
    return NamedSharding(mesh, P(axis_name))


def local_batch_sharding(axis_name: str = 'batch') -> NamedSharding | None:
    """Sample-axis sharding over all local devices, or None on single-device
    hosts (sharding a 1-device mesh only adds dispatch overhead).

    The default upload path of ``runtime.jax_backend`` (``DaisExecutor`` /
    ``PipelineExecutor`` ``__call__``) uses this so sample batches shard over
    every local chip without the caller building a mesh.
    """
    if jax.local_device_count() <= 1:
        return None
    return batch_sharding(default_mesh(axis_name, jax.local_devices()))


def pad_to_multiple(x: np.ndarray, multiple: int, axis: int = 0) -> tuple[np.ndarray, int]:
    """Pad axis length up to a device-count multiple; returns (padded, n_pad)."""
    n = x.shape[axis]
    n_pad = (-n) % multiple
    if n_pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, n_pad)
    return np.pad(x, widths), n_pad


def shard_batch(x: np.ndarray, mesh: Mesh | None = None, axis_name: str = 'batch'):
    """Place a host batch on the mesh, sharded along the sample axis.

    Pads the batch to a multiple of the device count; returns (array, n_pad)
    so callers can strip padding from results.
    """
    mesh = mesh if mesh is not None else default_mesh(axis_name)
    x, n_pad = pad_to_multiple(np.asarray(x), mesh.devices.size, axis=0)
    return jax.device_put(x, batch_sharding(mesh, axis_name)), n_pad


def device_inventory() -> dict:
    """Local device/process topology as a JSON-able dict — the ``/statusz``
    ``devices`` section (docs/observability.md). Callers must only invoke
    this when jax is already initialized: it touches the backend."""
    devices = jax.local_devices()
    try:
        process_count = jax.process_count()
    except Exception:
        process_count = 1
    return {
        'backend': jax.default_backend(),
        'process_count': process_count,
        'local_device_count': len(devices),
        'local_devices': [
            {'id': d.id, 'platform': d.platform, 'kind': getattr(d, 'device_kind', '?')} for d in devices
        ],
    }


from .distributed import global_mesh, initialize as initialize_distributed  # noqa: E402

_CAMPAIGN_API = (
    'run_campaign',
    'participate',
    'worker_loop',
    'chaos_drill',
    'create_campaign',
    'collect_results',
    'results_to_pipelines',
    'campaign_status',
    'CampaignError',
)


def __getattr__(name):
    # the campaign driver pulls in the solver + reliability stack; resolve
    # lazily so mesh utilities stay cheap to import
    if name in _CAMPAIGN_API:
        from . import campaign

        return getattr(campaign, name)
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')


__all__ = [
    'default_mesh',
    'batch_sharding',
    'local_batch_sharding',
    'shard_batch',
    'pad_to_multiple',
    'global_mesh',
    'initialize_distributed',
    'device_inventory',
    *_CAMPAIGN_API,
]
