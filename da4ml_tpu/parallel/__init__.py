"""Device-mesh utilities: batch sharding for inference, candidate sharding for search.

The framework's two parallel axes (SURVEY.md §2.6):
  - DAIS batch inference  -> shard the sample axis over the mesh
  - CMVM candidate search -> shard the (matrix × dc × restart) axis

Both ride XLA collectives over ICI; no custom transport.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def default_mesh(axis_name: str = 'batch', devices=None) -> Mesh:
    """A 1D mesh over all local devices."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devices.reshape(-1), (axis_name,))


def resolve_mesh(axis_name: str = 'batch', tpu_only: bool = True) -> Mesh | None:
    """The one ``DA4ML_JAX_MESH`` policy, shared by the CMVM search's
    ``_auto_mesh`` and the runtime (docs/api.md#environment-knobs):

    - ``DA4ML_JAX_MESH=0`` — never build a mesh;
    - ``DA4ML_JAX_MESH=1`` — build one on any multi-device backend;
    - unset — multi-device TPU backends only when ``tpu_only`` (the
      default: CPU/GPU "devices" are usually host threads where sharding
      only adds dispatch overhead); ``tpu_only=False`` drops the backend
      check for callers that already decided to shard (forced model
      sharding, tests on the 8-device CPU mesh).

    Returns a 1-D ``(axis_name,)`` mesh over all local devices, or None.
    """
    env = os.environ.get('DA4ML_JAX_MESH', '').strip()
    if env == '0':
        return None
    if tpu_only and env != '1':
        try:
            if jax.default_backend() != 'tpu':
                return None
        except Exception:
            return None
    try:
        devs = jax.local_devices()
    except Exception:
        return None
    if len(devs) < 2:
        return None
    return Mesh(np.asarray(devs), (axis_name,))


def model_mesh(k: int) -> Mesh | None:
    """A 2-D ``('batch', 'model')`` mesh with ``k`` devices on the model
    axis, or None when the topology cannot host it (fewer than ``k``
    local devices, device count not divisible by ``k``, ``k < 2``, or
    meshes disabled via ``DA4ML_JAX_MESH=0``). The sample axis keeps the
    remaining devices data-parallel."""
    if k < 2 or os.environ.get('DA4ML_JAX_MESH', '').strip() == '0':
        return None
    try:
        devs = jax.local_devices()
    except Exception:
        return None
    n = len(devs)
    if n < k or n % k:
        return None
    return Mesh(np.asarray(devs).reshape(n // k, k), ('batch', 'model'))


def batch_sharding(mesh: Mesh, axis_name: str = 'batch') -> NamedSharding:
    """Shard the leading (sample) axis; everything else replicated."""
    return NamedSharding(mesh, P(axis_name))


def local_batch_sharding(axis_name: str = 'batch') -> NamedSharding | None:
    """Sample-axis sharding over all local devices, or None on single-device
    hosts (sharding a 1-device mesh only adds dispatch overhead).

    The default upload path of ``runtime.jax_backend`` (``DaisExecutor`` /
    ``PipelineExecutor`` ``__call__``) uses this so sample batches shard over
    every local chip without the caller building a mesh.
    """
    if jax.local_device_count() <= 1:
        return None
    return batch_sharding(default_mesh(axis_name, jax.local_devices()))


def pad_to_multiple(x: np.ndarray, multiple: int, axis: int = 0) -> tuple[np.ndarray, int]:
    """Pad axis length up to a device-count multiple; returns (padded, n_pad)."""
    n = x.shape[axis]
    n_pad = (-n) % multiple
    if n_pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, n_pad)
    return np.pad(x, widths), n_pad


def shard_batch(x: np.ndarray, mesh: Mesh | None = None, axis_name: str = 'batch'):
    """Place a host batch on the mesh, sharded along the sample axis.

    Pads the batch to a multiple of the device count; returns (array, n_pad)
    so callers can strip padding from results.
    """
    mesh = mesh if mesh is not None else default_mesh(axis_name)
    x, n_pad = pad_to_multiple(np.asarray(x), mesh.devices.size, axis=0)
    return jax.device_put(x, batch_sharding(mesh, axis_name)), n_pad


def device_inventory() -> dict:
    """Local device/process topology as a JSON-able dict — the ``/statusz``
    ``devices`` section (docs/observability.md). Callers must only invoke
    this when jax is already initialized: it touches the backend."""
    devices = jax.local_devices()
    try:
        process_count = jax.process_count()
    except Exception:
        process_count = 1
    return {
        'backend': jax.default_backend(),
        'process_count': process_count,
        'local_device_count': len(devices),
        'local_devices': [
            {'id': d.id, 'platform': d.platform, 'kind': getattr(d, 'device_kind', '?')} for d in devices
        ],
    }


from .distributed import global_mesh, initialize as initialize_distributed  # noqa: E402

_CAMPAIGN_API = (
    'run_campaign',
    'participate',
    'worker_loop',
    'chaos_drill',
    'create_campaign',
    'collect_results',
    'results_to_pipelines',
    'campaign_status',
    'CampaignError',
)


def __getattr__(name):
    # the campaign driver pulls in the solver + reliability stack; resolve
    # lazily so mesh utilities stay cheap to import
    if name in _CAMPAIGN_API:
        from . import campaign

        return getattr(campaign, name)
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')


__all__ = [
    'default_mesh',
    'resolve_mesh',
    'model_mesh',
    'batch_sharding',
    'local_batch_sharding',
    'shard_batch',
    'pad_to_multiple',
    'global_mesh',
    'initialize_distributed',
    'device_inventory',
    *_CAMPAIGN_API,
]
