"""Global content-addressed solution store: solve once, serve everywhere.

At fleet scale most CMVM kernels are repeats — the same quantized layers
solved again and again — yet checkpoints (``reliability.checkpoint``) are
campaign-local. This module is the shared tier: a directory (local disk,
NFS, GCS-fuse) mapping the *full* kernel digest + canonical solver options
(:func:`store_key`) to a solved DAIS program, layered on the PR-1/7
atomic-write + lease primitives. The TVM split between an ahead-of-time
optimizer and a lightweight runtime (arxiv 1802.04799) is the precedent;
the bit-exactness contract of the paper (arxiv 2507.04535) sets the rule
that makes a shared cache safe: **never trust a cached byte the verifier
has not re-validated**.

Layout (one store = one directory)::

    <root>/solutions/<digest[:2]>/<digest>.json   entry docs (atomic writes)
    <root>/corrupt/<digest>.<ms>.json             quarantined bad entries
    <root>/negative/<digest>.json                 TTL'd failed-solve markers
    <root>/leases/<digest>.lease                  single-flight claims (.lease)

Robustness model (docs/store.md):

- **verify-on-read** — every entry is parsed, schema-checked, and run
  through the ``analysis`` verifier before use; any failure (bit flip,
  truncation, stale schema) quarantines the file to ``corrupt/`` and the
  caller transparently re-solves. A corrupted store can cost wall clock,
  never a wrong program.
- **single-flight** — concurrent cold misses on one key collapse to one
  search through a short-TTL lease (``reliability.lease``); waiters poll
  with deadline-aware backoff and fall through to a local solve if the
  winner dies (the steal machinery covers the crash case) or the deadline
  nears.
- **negative caching** — a solve that failed terminally writes a TTL'd
  marker so a poisonous kernel cannot DoS the fleet with repeated
  searches; the marker expires and the key becomes retryable.
- **graceful degradation** — an unreachable or read-only store degrades to
  the plain local-solve path behind a ``store.read``/``store.write``
  breaker pair with one-time warnings; it never fails a solve.

Fault sites (``DA4ML_FAULT_INJECT``, docs/reliability.md): ``store.read``
(error modes = unreachable store; mode ``corrupt`` = torn read),
``store.write`` (error modes = unwritable store; ``corrupt`` = torn entry
on disk), ``store.verify`` (``corrupt`` = semantic in-memory mutation that
only the verifier catches — the deterministic bit-flip drill).
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Callable, NamedTuple

from .. import telemetry
from ..ir.comb import Pipeline
from ..reliability.breaker import breaker_for
from ..reliability.checkpoint import atomic_write_bytes, fsync_dir, kernel_digest
from ..reliability.errors import BackendUnavailable, ReliabilityError, SolveTimeout, classify
from ..reliability.faults import fault_active, fault_check
from ..reliability.lease import DEFAULT_GRACE_S, claim_lease, default_owner, release_lease, renew_lease
from ..reliability.locktrace import make_lock

_VERSION = 1

_ENV_VAR = 'DA4ML_SOLUTION_STORE'

#: failed-solve markers expire after this many seconds (DA4ML_STORE_NEGATIVE_TTL_S)
DEFAULT_NEGATIVE_TTL_S = 300.0

#: single-flight lease TTL: one search window; waiters steal after expiry + grace
DEFAULT_LEASE_TTL_S = 15.0


class StoreEntryCorrupt(ReliabilityError):
    """A store entry exists but failed parse/schema/verification — it is
    quarantined, never served."""


class StoreNegativeEntry(BackendUnavailable):
    """The store holds a live negative-cache marker for this key: a recent
    solve failed terminally on every backend, so re-searching now would
    only repeat the failure. Classified ``fallback``; retry after the
    marker's TTL."""

    def __init__(self, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


# --------------------------------------------------------------------- keys

#: ``cmvm.api.solve`` signature defaults for every option that shapes the
#: solution — applied before hashing so a sparse options dict (campaign
#: manifests) and an explicit-defaults call (``solve()``) agree on the key
_SOLVE_DEFAULTS: dict = {
    'method0': 'wmc',
    'method1': 'auto',
    'hard_dc': -1,
    'decompose_dc': -2,
    'qintervals': None,
    'latencies': None,
    'adder_size': -1,
    'carry_size': -1,
    'search_all_decompose_dc': True,
    'method0_candidates': None,
    'n_restarts': 1,
    'quality': None,
}


def canonical_solve_opts(solve_kwargs: dict | None) -> dict:
    """Canonical (JSON-stable) form of the solver options that shape a
    solution: signature defaults applied, qintervals listified, the quality
    knob reduced via :func:`~..cmvm.search.spec.quality_key` (the fast
    default drops out entirely)."""
    from ..reliability.orchestrator import _checkpoint_opts

    kw = dict(_SOLVE_DEFAULTS)
    for k, v in (solve_kwargs or {}).items():
        if k in _SOLVE_DEFAULTS:
            kw[k] = v
    opts = _checkpoint_opts(kw)
    if opts.get('n_restarts') in (None, 0):
        opts['n_restarts'] = 1
    return opts


def store_key(kernel, backend: str = 'auto', solve_kwargs: dict | None = None) -> str:
    """The global store key: full sha256 digest over the kernel bytes, the
    canonical solver options, and the *canonical backend name* — solves are
    deterministic per backend, so an entry solved on ``pure-python`` must
    never answer a ``jax`` request (byte-identity would silently break).
    ``backend='auto'`` resolves to the backend this host would really use,
    exactly as ``cmvm.api.solve`` does."""
    from ..reliability.orchestrator import canonical_backend

    return kernel_digest(
        kernel,
        {
            'store_version': _VERSION,
            'backend': canonical_backend(backend),
            'solver_options': canonical_solve_opts(solve_kwargs),
        },
    )


# --------------------------------------------------------------------- store


class StoreHit(NamedTuple):
    """One verified store read: the program plus its entry document."""

    key: str
    pipeline: Pipeline
    doc: dict


class _Renewer(threading.Thread):
    """Renews the single-flight lease at ttl/3 cadence while the winner
    searches (daemon: dies with the process, which is exactly what lets a
    waiter steal and take over)."""

    def __init__(self, lease, interval_s: float):
        super().__init__(name=f'da4ml-store-renew-{lease.key[:8]}', daemon=True)
        self.lease = lease
        self.interval_s = interval_s
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                if not renew_lease(self.lease):
                    return
            except OSError:  # store went unreachable mid-solve; publish will cope
                return

    def stop(self) -> None:
        self._stop.set()


class SolutionStore:
    """One content-addressed solution store directory.

    ``readonly=True`` (or ``DA4ML_STORE_RO=1``) serves hits but never
    writes — no publishes, no negative markers, no single-flight
    coordination (a reader must not create lease files on, say, a
    snapshotted release artifact)."""

    def __init__(
        self,
        root: str | os.PathLike,
        negative_ttl_s: float | None = None,
        lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
        readonly: bool | None = None,
    ):
        self.root = Path(root)
        if negative_ttl_s is None:
            try:
                negative_ttl_s = float(os.environ.get('DA4ML_STORE_NEGATIVE_TTL_S', '') or DEFAULT_NEGATIVE_TTL_S)
            except ValueError:
                negative_ttl_s = DEFAULT_NEGATIVE_TTL_S
        self.negative_ttl_s = negative_ttl_s
        self.lease_ttl_s = lease_ttl_s
        if readonly is None:
            readonly = os.environ.get('DA4ML_STORE_RO', '') in ('1', 'true', 'on')
        self.readonly = readonly
        self.solutions_dir = self.root / 'solutions'
        self.corrupt_dir = self.root / 'corrupt'
        self.negative_dir = self.root / 'negative'
        self.leases_dir = self.root / 'leases'

    # -- paths ---------------------------------------------------------------

    def _entry_path(self, key: str) -> Path:
        return self.solutions_dir / key[:2] / f'{key}.json'

    def _negative_path(self, key: str) -> Path:
        return self.negative_dir / f'{key}.json'

    # -- breakers ------------------------------------------------------------

    @staticmethod
    def _read_breaker():
        return breaker_for('store.read')

    @staticmethod
    def _write_breaker():
        return breaker_for('store.write')

    def degraded(self) -> bool:
        """True while either store breaker is open — callers skip the store
        entirely (the one-time warning already fired)."""
        return self._read_breaker().state == 'open' or self._write_breaker().state == 'open'

    # -- read path -----------------------------------------------------------

    def _quarantine(self, key: str, path: Path, reason: str) -> None:
        """Move a bad entry to the ``corrupt/`` sidecar so it is never read
        again; the caller re-solves. Best-effort on read-only filesystems
        (the entry then stays, fails verification on every read, and every
        read falls through to a local solve — slow, never wrong)."""
        telemetry.counter('store.corrupt_quarantined').inc()
        telemetry.instant('store.quarantine', key=key[:16], reason=reason[:200])
        telemetry.warn_once(
            f'store.corrupt.{key[:16]}',
            f'solution store entry {key[:16]}… failed verification ({reason[:120]}); quarantined, re-solving',
            logger='store',
        )
        dest = self.corrupt_dir / f'{key}.{int(time.time() * 1000)}.json'
        try:
            dest.parent.mkdir(parents=True, exist_ok=True)
            os.replace(path, dest)
            fsync_dir(dest.parent)
        except OSError:
            pass

    def _read(self, key: str) -> StoreHit | None:
        """Read + schema-check + verify one entry; quarantine on any
        failure. No hit/miss accounting (that is :meth:`lookup`'s job — the
        single-flight poll loop reads without skewing the hit ratio)."""
        br = self._read_breaker()
        if not br.allow():
            telemetry.warn_once(
                'store.read.breaker',
                f'solution store {self.root} unreachable (store.read breaker open); degrading to local solves',
                logger='store',
            )
            return None
        path = self._entry_path(key)
        try:
            fault_check('store.read')
            raw = path.read_bytes()
        except FileNotFoundError:
            br.record_success()
            return None
        except Exception as e:  # noqa: BLE001 - any store I/O failure degrades, never propagates
            br.record_failure()
            telemetry.counter('store.read_errors').inc()
            telemetry.warn_once(
                'store.read.error',
                f'solution store read failed ({type(e).__name__}: {e}); degrading to local solves',
                logger='store',
            )
            return None
        br.record_success()
        if fault_active('store.read', 'corrupt'):
            raw = raw[: max(1, len(raw) // 2)]  # torn/truncated read drill
        try:
            doc = json.loads(raw)
            if not isinstance(doc, dict) or 'pipeline' not in doc:
                raise StoreEntryCorrupt('not a store entry document')
            if doc.get('version') != _VERSION:
                raise StoreEntryCorrupt(f'stale schema version {doc.get("version")!r}')
            if doc.get('key') not in (None, key):
                raise StoreEntryCorrupt(f'key mismatch: entry claims {str(doc.get("key"))[:16]}…')
            if fault_active('store.verify', 'corrupt'):
                # semantic bit-flip drill: a mutation that parses fine and
                # only the verifier catches (out_idx past the buffer end)
                doc['pipeline']['stages'][-1]['out_idxs'][0] = 10**6
            pipe = Pipeline.from_dict(doc['pipeline'], verify=False)
            from ..analysis import verify

            res = verify(pipe)
            if not res.ok:
                raise StoreEntryCorrupt(f'verifier rejected entry: {res.errors[0]}')
        except Exception as e:  # noqa: BLE001 - any bad byte means quarantine
            self._quarantine(key, path, f'{type(e).__name__}: {e}')
            return None
        if not self.readonly:
            try:
                os.utime(path)  # LRU signal for gc (best-effort)
            except OSError:
                pass
        return StoreHit(key=key, pipeline=pipe, doc=doc)

    def lookup(self, key: str) -> StoreHit | None:
        """One accounted store probe: verified hit or None (miss/degraded)."""
        t0 = time.perf_counter()
        hit = self._read(key)
        telemetry.histogram('store.lookup_s').observe(time.perf_counter() - t0)
        telemetry.counter('store.hits' if hit is not None else 'store.misses').inc()
        return hit

    # -- write path ----------------------------------------------------------

    def publish(self, key: str, pipeline: Pipeline, meta: dict | None = None) -> bool:
        """Write one solved entry (atomic + durable). Returns False — with a
        one-time warning, never an exception — when the store is read-only,
        breaker-open, or the write fails. Publishes are idempotent: a solve
        is deterministic per backend, so concurrent publishers rewrite
        identical bytes."""
        if self.readonly:
            telemetry.warn_once(
                'store.readonly',
                f'solution store {self.root} is read-only; solves are not published',
                logger='store',
            )
            return False
        br = self._write_breaker()
        if not br.allow():
            telemetry.warn_once(
                'store.write.breaker',
                f'solution store {self.root} unwritable (store.write breaker open); solves are not published',
                logger='store',
            )
            return False
        doc = {
            'version': _VERSION,
            'key': key,
            'cost': float(pipeline.cost),
            'created_at': round(time.time(), 3),
            **{k: v for k, v in (meta or {}).items() if k not in ('version', 'key', 'pipeline')},
            'pipeline': pipeline.to_dict(),
        }
        payload = json.dumps(doc, sort_keys=True)
        if fault_active('store.write', 'corrupt'):
            payload = payload[: max(1, len(payload) // 2)]  # torn write drill
        try:
            fault_check('store.write')
            atomic_write_bytes(self._entry_path(key), payload.encode())
        except Exception as e:  # noqa: BLE001 - any store I/O failure degrades, never propagates
            br.record_failure()
            telemetry.counter('store.write_errors').inc()
            telemetry.warn_once(
                'store.write.error',
                f'solution store publish failed ({type(e).__name__}: {e}); continuing without the store',
                logger='store',
            )
            return False
        br.record_success()
        telemetry.counter('store.publishes').inc()
        try:  # a successful solve clears any stale negative marker
            self._negative_path(key).unlink()
        except OSError:
            pass
        return True

    # -- negative cache ------------------------------------------------------

    def negative_lookup(self, key: str) -> dict | None:
        """A live (unexpired) failed-solve marker, or None. Expired markers
        are opportunistically removed."""
        try:
            doc = json.loads(self._negative_path(key).read_text())
            expires_at = float(doc['expires_at'])
        except (OSError, ValueError, KeyError, TypeError):
            return None
        if time.time() >= expires_at:
            if not self.readonly:
                try:
                    self._negative_path(key).unlink()
                except OSError:
                    pass
            return None
        telemetry.counter('store.negative_hits').inc()
        return doc

    def publish_negative(self, key: str, error: BaseException | str, ttl_s: float | None = None) -> bool:
        """Record a terminal solve failure so the fleet stops re-searching
        this key until the TTL passes."""
        if self.readonly or not self._write_breaker().allow():
            return False
        ttl = self.negative_ttl_s if ttl_s is None else ttl_s
        doc = {
            'version': _VERSION,
            'key': key,
            'error': (f'{type(error).__name__}: {error}' if isinstance(error, BaseException) else str(error))[:300],
            'created_at': round(time.time(), 3),
            'expires_at': round(time.time() + ttl, 3),
        }
        try:
            atomic_write_bytes(self._negative_path(key), json.dumps(doc, sort_keys=True).encode())
        except OSError:
            self._write_breaker().record_failure()
            return False
        self._write_breaker().record_success()
        telemetry.counter('store.negative_publishes').inc()
        return True

    # -- single-flight solve -------------------------------------------------

    def solve_through(
        self,
        key: str,
        cold_solve: Callable[[], Pipeline],
        meta: dict | None = None,
        deadline_s: float | None = None,
        info: dict | None = None,
        publish_ok: Callable[[], bool] | None = None,
    ) -> Pipeline:
        """The store-mediated solve: verified hit, else single-flighted cold
        solve + publish.

        ``cold_solve`` runs the real search (it must NOT consult the store
        again). ``info`` (optional dict) receives ``source`` (``'store'`` /
        ``'solve'``) and ``singleflight_wait`` for callers that report
        provenance. ``publish_ok`` (evaluated after a successful cold solve)
        vetoes the publish — the orchestrator's fallback chain may answer
        from a *different* backend than the key encodes, and determinism is
        per-backend, so such a result must not be published under this key.
        Raises :class:`StoreNegativeEntry` on a live negative marker;
        everything else degrades to ``cold_solve()``."""
        if info is None:
            info = {}
        hit = self.lookup(key)
        if hit is not None:
            info.update(source='store', backend=hit.doc.get('backend'), cost=hit.doc.get('cost'))
            return hit.pipeline
        neg = self.negative_lookup(key)
        if neg is not None:
            remaining = max(float(neg.get('expires_at', 0.0)) - time.time(), 0.0)
            raise StoreNegativeEntry(
                f'solve of {key[:16]}… recently failed on every backend ({neg.get("error")}); '
                f'negative-cache marker expires in {remaining:.0f}s',
                retry_after_s=remaining,
            )
        if self.readonly or self.degraded():
            # no coordination possible/worthwhile: plain local solve
            result = cold_solve()
            info['source'] = 'solve'
            if publish_ok is None or publish_ok():
                self.publish(key, result, meta=meta)
            return result

        deadline_t = time.monotonic() + deadline_s if deadline_s is not None and deadline_s > 0 else None
        grace = max(DEFAULT_GRACE_S, self.lease_ttl_s / 3)
        backoff = 0.05
        waited = False
        while True:
            lease = None
            try:
                # per-THREAD owner: the default (host:pid) owner would let
                # every thread of one process adopt the same live lease and
                # the in-process herd would not collapse
                lease = claim_lease(
                    self.leases_dir,
                    key,
                    owner=f'{default_owner()}:t{threading.get_ident()}',
                    ttl_s=self.lease_ttl_s,
                    grace_s=grace,
                )
            except OSError:
                break  # store went unreachable between lookup and claim
            if lease is not None:
                return self._solve_as_winner(key, lease, cold_solve, meta, info, publish_ok)
            # waiter: someone else is searching this key right now
            if not waited:
                waited = True
                info['singleflight_wait'] = True
                telemetry.counter('store.singleflight_waits').inc()
            if deadline_t is not None and time.monotonic() + backoff >= deadline_t - 0.05:
                telemetry.counter('store.singleflight_fallthroughs').inc()
                break  # deadline-aware fall-through: solve locally, now
            time.sleep(backoff)
            backoff = min(backoff * 1.6, 0.4)
            hit = self._read(key)
            if hit is not None:
                telemetry.counter('store.hits').inc()
                info.update(source='store', backend=hit.doc.get('backend'), cost=hit.doc.get('cost'))
                return hit.pipeline
            neg = self.negative_lookup(key)
            if neg is not None:
                raise StoreNegativeEntry(
                    f'solve of {key[:16]}… failed on every backend ({neg.get("error")})',
                    retry_after_s=max(float(neg.get('expires_at', 0.0)) - time.time(), 0.0),
                )
            # loop: the winner's lease may have expired (it died) — the next
            # claim_lease steals it and this caller becomes the winner
        result = cold_solve()
        info['source'] = 'solve'
        if publish_ok is None or publish_ok():
            self.publish(key, result, meta=meta)
        return result

    def _solve_as_winner(self, key, lease, cold_solve, meta, info, publish_ok=None) -> Pipeline:
        renewer = _Renewer(lease, interval_s=self.lease_ttl_s / 3.0)
        renewer.start()
        try:
            hit = self._read(key)  # published between our miss and the claim?
            if hit is not None:
                telemetry.counter('store.hits').inc()
                info.update(source='store', backend=hit.doc.get('backend'), cost=hit.doc.get('cost'))
                return hit.pipeline
            try:
                result = cold_solve()
            except BaseException as exc:
                # terminal failures become negative markers; a blown
                # deadline does not (another caller with more budget may
                # still succeed)
                if not isinstance(exc, SolveTimeout) and classify(exc) in ('fatal', 'fallback'):
                    self.publish_negative(key, exc)
                raise
            info['source'] = 'solve'
            if publish_ok is None or publish_ok():
                self.publish(key, result, meta=meta)
            return result
        finally:
            renewer.stop()
            try:
                release_lease(lease)
            except OSError:
                pass

    # -- maintenance ---------------------------------------------------------

    def _entries(self) -> list[tuple[Path, os.stat_result]]:
        out = []
        try:
            shards = sorted(os.scandir(self.solutions_dir), key=lambda e: e.name)
        except OSError:
            return out
        for shard in shards:
            if not shard.is_dir():
                continue
            try:
                for e in os.scandir(shard.path):
                    if e.name.endswith('.json') and not e.name.startswith('.'):
                        try:
                            out.append((Path(e.path), e.stat()))
                        except OSError:
                            continue
            except OSError:
                continue
        return out

    def occupancy(self) -> dict:
        """Entry/byte counts (the /statusz store panel; scrape-safe)."""
        entries = self._entries()

        def _count(d: Path) -> int:
            try:
                return sum(1 for e in os.scandir(d) if e.name.endswith('.json'))
            except OSError:
                return 0

        return {
            'root': str(self.root),
            'entries': len(entries),
            'bytes': int(sum(st.st_size for _, st in entries)),
            'negative': _count(self.negative_dir),
            'corrupt': _count(self.corrupt_dir),
            'readonly': self.readonly,
        }

    def stats(self) -> dict:
        """Occupancy + this process's hit/miss accounting (cache CLI)."""
        from ..telemetry.metrics import metrics_snapshot

        snap = metrics_snapshot()

        def _c(name: str) -> float:
            m = snap.get(name)
            return float(m.get('value', 0.0)) if m else 0.0

        hits, misses = _c('store.hits'), _c('store.misses')
        out = self.occupancy()
        out.update(
            {
                'hits': int(hits),
                'misses': int(misses),
                'hit_ratio': round(hits / (hits + misses), 4) if hits + misses else None,
                'negative_hits': int(_c('store.negative_hits')),
                'corrupt_quarantined': int(_c('store.corrupt_quarantined')),
                'singleflight_waits': int(_c('store.singleflight_waits')),
                'breakers': {
                    'store.read': self._read_breaker().state,
                    'store.write': self._write_breaker().state,
                },
            }
        )
        return out

    def verify_all(self) -> dict:
        """Re-verify every entry (``da4ml-tpu cache verify``); bad entries
        are quarantined exactly as a read would."""
        checked = ok = 0
        for path, _ in self._entries():
            checked += 1
            if self._read(path.name[: -len('.json')]) is not None:
                ok += 1
        return {'checked': checked, 'ok': ok, 'quarantined': checked - ok}

    def gc(self, max_bytes: int | None = None, max_age_s: float | None = None) -> dict:
        """Lease-guarded LRU eviction: drop entries older than ``max_age_s``
        and then the least-recently-used until under ``max_bytes``. The run
        is serialized on a ``__gc__`` lease; each victim is evicted only
        under its own single-flight lease, so gc never unlinks an entry a
        solver is concurrently publishing or about to serve. Expired
        negative markers and old quarantine files are purged too."""
        report = {'evicted': 0, 'freed_bytes': 0, 'negatives_purged': 0, 'corrupt_purged': 0, 'skipped_live': 0}
        if self.readonly:
            report['skipped'] = 'store is read-only'
            return report
        guard = claim_lease(self.leases_dir, '__gc__', ttl_s=max(self.lease_ttl_s, 30.0))
        if guard is None:
            report['skipped'] = 'another gc run holds the lock'
            return report
        now = time.time()
        try:
            # expired negative markers
            try:
                for e in os.scandir(self.negative_dir):
                    try:
                        doc = json.loads(Path(e.path).read_text())
                        if now >= float(doc.get('expires_at', 0.0)):
                            os.unlink(e.path)
                            report['negatives_purged'] += 1
                    except (OSError, ValueError, TypeError):
                        continue
            except OSError:
                pass
            # old quarantine sidecars age out with max_age_s
            if max_age_s is not None:
                try:
                    for e in os.scandir(self.corrupt_dir):
                        try:
                            if now - e.stat().st_mtime > max_age_s:
                                os.unlink(e.path)
                                report['corrupt_purged'] += 1
                        except OSError:
                            continue
                except OSError:
                    pass
            entries = sorted(self._entries(), key=lambda t: t[1].st_mtime)  # oldest first
            total = sum(st.st_size for _, st in entries)
            report['entries_before'], report['bytes_before'] = len(entries), int(total)
            victims: list[tuple[Path, os.stat_result]] = []
            if max_age_s is not None:
                victims += [(p, st) for p, st in entries if now - st.st_mtime > max_age_s]
            if max_bytes is not None and total > max_bytes:
                over = total - sum(st.st_size for _, st in victims)
                for p, st in entries:
                    if over <= max_bytes:
                        break
                    if (p, st) not in victims:
                        victims.append((p, st))
                        over -= st.st_size
            for path, st in victims:
                key = path.name[: -len('.json')]
                lease = claim_lease(self.leases_dir, key, ttl_s=5.0)
                if lease is None:
                    report['skipped_live'] += 1  # a solver holds this key right now
                    continue
                try:
                    path.unlink()
                    report['evicted'] += 1
                    report['freed_bytes'] += int(st.st_size)
                except OSError:
                    pass
                finally:
                    release_lease(lease)
            telemetry.counter('store.gc_evictions').inc(report['evicted'])
        finally:
            release_lease(guard)
        report['entries_after'] = report['entries_before'] - report['evicted']
        report['bytes_after'] = report['bytes_before'] - report['freed_bytes']
        return report


# ----------------------------------------------------------------- resolution

_stores: dict[str, SolutionStore] = {}
_stores_lock = make_lock('store.registry')


def store_at(path: str | os.PathLike, **kw) -> SolutionStore:
    """Process-wide :class:`SolutionStore` per resolved directory."""
    key = str(Path(path).expanduser().resolve())
    with _stores_lock:
        store = _stores.get(key)
        if store is None:
            _stores[key] = store = SolutionStore(key, **kw)
        return store


def default_store() -> SolutionStore | None:
    """The ``DA4ML_SOLUTION_STORE`` store, or None when unset. With
    ``DA4ML_STORE_LOCAL_TIER`` also set, the env store is opened as a
    :class:`~.tiered.TieredStore` (in-proc LRU → local disk → shared FS)
    so every ``resolve_store`` caller — ``solve(store=)``, campaign
    workers, ``POST /v1/solve`` replicas — reads through the tiers."""
    env = os.environ.get(_ENV_VAR, '').strip()
    if not env:
        return None
    from .tiered import local_tier_env, tiered_at

    local = local_tier_env()
    if local:
        return tiered_at(env, local)
    return store_at(env)


def resolve_store(store) -> SolutionStore | None:
    """Normalize a ``store=`` argument: None → the env-configured default,
    ``False`` → disabled (even with the env set — the cold-solve escape
    hatch), a path → opened, a :class:`SolutionStore` → itself. An explicit
    path honors ``DA4ML_STORE_LOCAL_TIER`` the same way the env default
    does — a fleet replica handed ``--solve-store`` must still read through
    its local cache tier (docs/store.md#tiers)."""
    if store is False:
        return None
    if store is None:
        return default_store()
    if isinstance(store, SolutionStore):
        return store
    from .tiered import local_tier_env, tiered_at

    local = local_tier_env()
    if local:
        return tiered_at(store, local)
    return store_at(store)


def reset_store_registry() -> None:
    """Drop cached store handles (test isolation)."""
    with _stores_lock:
        _stores.clear()


# ------------------------------------------------------------------- health


def store_health() -> dict | None:
    """The /healthz ``store`` check (None when no store was opened in this
    process). Resolved via ``sys.modules`` by ``telemetry.obs.health`` so a
    scrape never imports this module."""
    with _stores_lock:
        stores = list(_stores.values())
    if not stores:
        return None
    breakers = {n: breaker_for(n).state for n in ('store.read', 'store.write')}
    degraded = any(s == 'open' for s in breakers.values())
    return {
        'status': 'degraded' if degraded else 'ok',
        'breakers': breakers,
        'stores': [s.occupancy() for s in stores],
    }


def store_status() -> dict | None:
    """The /statusz ``store`` panel: occupancy + hit ratio (None when no
    store was opened in this process)."""
    with _stores_lock:
        stores = list(_stores.values())
    if not stores:
        return None
    from ..telemetry.metrics import metrics_snapshot

    snap = metrics_snapshot()

    def _c(name: str) -> float:
        m = snap.get(name)
        return float(m.get('value', 0.0)) if m else 0.0

    hits, misses = _c('store.hits'), _c('store.misses')
    return {
        'stores': [s.occupancy() for s in stores],
        'hits': int(hits),
        'misses': int(misses),
        'negative_hits': int(_c('store.negative_hits')),
        'corrupt_quarantined': int(_c('store.corrupt_quarantined')),
        'singleflight_waits': int(_c('store.singleflight_waits')),
        'hit_ratio': round(hits / (hits + misses), 4) if hits + misses else None,
    }
