"""Solution-store chaos drill: zipf fleet traffic + a mid-run bit flip.

The CI gate (job ``store-chaos``, ``da4ml-tpu cache chaos``) for the store's
whole robustness contract at once:

1. a deterministic corpus of kernels and a zipf-weighted request stream
   (real fleets re-solve the same hot layers over and over) is split across
   ``workers`` subprocesses sharing one store directory;
2. every worker's slice starts with the same *sentinel* kernel no other
   request draws, so all workers race it cold simultaneously — the
   single-flight gate: exactly one may actually search it;
3. the parent corrupts the hottest key's entry on disk mid-run (truncated,
   exactly what a torn write or bit rot produces) — verify-on-read must
   quarantine it and re-solve transparently;
4. every response is digest-compared against single-process cold
   references computed with the store disabled.

Passes iff the corpus completed, every response is byte-identical to its
reference, the fleet hit rate is >= ``min_hit_rate``, the sentinel herd
collapsed to one search, at least one entry was quarantined, and the hit
path stayed bounded by lookup+verify (p99 against the cold p50).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

#: request-stream shape: steep zipf over a small corpus so the drawn
#: distinct-key count (the unavoidable cold misses) stays far under 10% of
#: the requests — the >=0.9 hit-rate gate then has real headroom
N_KERNELS = 48
N_REQUESTS = 300
ZIPF_A = 2.2
DRILL_SEED = 20260804


def _drill_corpus(n: int = N_KERNELS, dim: int = 6, bits: int = 3) -> list[np.ndarray]:
    rng = np.random.default_rng(DRILL_SEED)
    return [
        (rng.integers(0, 2**bits, (dim, dim)) * rng.choice([-1.0, 1.0], (dim, dim))).astype(np.float64)
        for _ in range(n)
    ]


def _request_indices(n_kernels: int = N_KERNELS, n_requests: int = N_REQUESTS) -> list[int]:
    """Zipf-weighted request stream over kernel ranks (deterministic)."""
    rng = np.random.default_rng(DRILL_SEED)
    w = 1.0 / np.arange(1, n_kernels + 1) ** ZIPF_A
    w /= w.sum()
    return [int(i) for i in rng.choice(n_kernels, size=n_requests, p=w)]


def _pipe_digest(pipe_doc: dict) -> str:
    return hashlib.sha256(json.dumps(pipe_doc, sort_keys=True).encode()).hexdigest()


def _percentile(values: list[float], q: float) -> float:
    return float(np.percentile(np.asarray(values, dtype=np.float64), q)) if values else 0.0


# ----------------------------------------------------------------- worker


def _worker_main(argv: list[str]) -> int:
    """``python -m da4ml_tpu.store.chaos --worker ...`` — replay one slice
    of the request stream through ``solve_through`` and print one JSON line
    of per-request records + this process's store counters."""
    import argparse

    ap = argparse.ArgumentParser(prog='da4ml_tpu.store.chaos')
    ap.add_argument('--worker', action='store_true', required=True)
    ap.add_argument('--store', required=True)
    ap.add_argument('--backend', default='pure-python')
    ap.add_argument('--indices', required=True, help='comma-separated corpus indices to request, in order')
    args = ap.parse_args(argv)

    from ..cmvm.api import solve
    from ..telemetry.metrics import enable_metrics, metrics_snapshot
    from .solution_store import store_at, store_key

    enable_metrics()
    corpus = _drill_corpus()
    store = store_at(args.store)
    records = []
    for idx in (int(i) for i in args.indices.split(',')):
        kernel = corpus[idx]
        key = store_key(kernel, args.backend)
        info: dict = {}

        def cold(kernel=kernel):
            return solve(kernel, backend=args.backend, store=False)

        t0 = time.perf_counter()
        pipe = store.solve_through(key, cold, meta={'backend': args.backend}, info=info)
        records.append(
            {
                'idx': idx,
                'digest': _pipe_digest(pipe.to_dict()),
                'source': info.get('source'),
                'waited': bool(info.get('singleflight_wait')),
                'ms': round((time.perf_counter() - t0) * 1e3, 3),
            }
        )
    snap = metrics_snapshot()

    def _c(name: str) -> int:
        m = snap.get(name)
        return int(m.get('value', 0)) if m else 0

    print(
        json.dumps(
            {
                'records': records,
                'counters': {
                    n: _c(f'store.{n}')
                    for n in ('hits', 'misses', 'singleflight_waits', 'corrupt_quarantined', 'negative_hits')
                },
            }
        ),
        flush=True,
    )
    return 0


# ------------------------------------------------------------------ drill


def _spawn(store_dir: str, backend: str, indices: list[int]) -> subprocess.Popen:
    from ..parallel.campaign import _repo_pythonpath

    env = _repo_pythonpath(dict(os.environ))
    env.pop('DA4ML_METRICS_PORT', None)
    env.pop('DA4ML_TRACE', None)
    env.pop('DA4ML_FAULT_INJECT', None)  # injected faults would break the herd gate
    cmd = [
        sys.executable,
        '-m',
        'da4ml_tpu.store.chaos',
        '--worker',
        '--store',
        store_dir,
        '--backend',
        backend,
        '--indices',
        ','.join(str(i) for i in indices),
    ]
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def store_chaos_drill(
    workers: int = 3,
    base_dir: str | os.PathLike | None = None,
    backend: str = 'pure-python',
    n_kernels: int = N_KERNELS,
    n_requests: int = N_REQUESTS,
    min_hit_rate: float = 0.9,
    timeout_s: float = 600.0,
) -> dict:
    """Run the store chaos drill; returns a report with ``ok`` + ``checks``."""
    import tempfile

    from ..cmvm.api import solve
    from .solution_store import store_at, store_key

    base = Path(base_dir) if base_dir is not None else Path(tempfile.mkdtemp(prefix='da4ml-store-chaos-'))
    store_dir = base / 'store'
    store_dir.mkdir(parents=True, exist_ok=True)
    corpus = _drill_corpus(n=n_kernels)
    indices = _request_indices(n_kernels=n_kernels, n_requests=n_requests)
    drawn = set(indices)
    # the sentinel: a kernel NO regular request draws, prepended to every
    # worker's slice so all workers race it cold at t=0
    sentinel = next(i for i in range(n_kernels - 1, -1, -1) if i not in drawn)
    slices = [[sentinel] + indices[i::workers] for i in range(workers)]

    report: dict = {
        'base_dir': str(base),
        'workers': workers,
        'n_kernels': n_kernels,
        'n_requests': n_requests,
        'distinct_keys': len(drawn) + 1,
        'sentinel': sentinel,
        'backend': backend,
    }

    # (1) cold references, store disabled — the byte-identity ground truth
    t0 = time.perf_counter()
    cold_ms: list[float] = []
    reference: dict[int, str] = {}
    for idx in sorted(drawn | {sentinel}):
        t_k = time.perf_counter()
        reference[idx] = _pipe_digest(solve(corpus[idx], backend=backend, store=False).to_dict())
        cold_ms.append((time.perf_counter() - t_k) * 1e3)
    report['reference_wall_s'] = round(time.perf_counter() - t0, 3)
    report['cold_p50_ms'] = round(_percentile(cold_ms, 50), 3)

    # (2) the fleet
    procs = [_spawn(str(store_dir), backend, sl) for sl in slices]

    # (3) mid-run bit flip: truncate the hottest key's entry once it lands
    # (the most-drawn index — guaranteed to be read again after the flip)
    hot_idx = max(drawn, key=indices.count)
    hot_key = store_key(corpus[hot_idx], backend)
    hot_path = store_dir / 'solutions' / hot_key[:2] / f'{hot_key}.json'
    flipped = False
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline and not flipped:
        if hot_path.exists():
            try:
                raw = hot_path.read_bytes()
                hot_path.write_bytes(raw[: max(1, len(raw) // 2)])
                flipped = True
            except OSError:
                pass
        if any(p.poll() is not None for p in procs) and not flipped:
            break  # a worker already finished; flip window closed
        if not flipped:
            time.sleep(0.02)
    report['bit_flipped'] = flipped

    worker_docs, failures = [], []
    for p in procs:
        try:
            out, err = p.communicate(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            failures.append({'pid': p.pid, 'rc': 'timeout', 'stderr': (err or '')[-300:]})
            continue
        doc = None
        for line in reversed((out or '').strip().splitlines()):
            if line.startswith('{'):
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                break
        if p.returncode == 0 and doc is not None:
            worker_docs.append(doc)
        else:
            failures.append({'pid': p.pid, 'rc': p.returncode, 'stderr': (err or '').strip()[-300:]})
    if failures:
        report['worker_failures'] = failures

    records = [r for doc in worker_docs for r in doc['records']]
    hits = sum(doc['counters']['hits'] for doc in worker_docs)
    misses = sum(doc['counters']['misses'] for doc in worker_docs)
    quarantined = sum(doc['counters']['corrupt_quarantined'] for doc in worker_docs)
    hit_rate = hits / (hits + misses) if hits + misses else 0.0
    mismatches = [r for r in records if r['digest'] != reference.get(r['idx'])]
    sentinel_solves = sum(1 for doc in worker_docs for r in doc['records'][:1] if r['source'] == 'solve')
    pure_hit_ms = [r['ms'] for r in records if r['source'] == 'store' and not r['waited']]
    hit_p99 = _percentile(pure_hit_ms, 99)

    occupancy = store_at(str(store_dir)).occupancy()
    report.update(
        {
            'n_records': len(records),
            'hits': hits,
            'misses': misses,
            'hit_rate': round(hit_rate, 4),
            'quarantined': quarantined,
            'sentinel_cold_solves': sentinel_solves,
            'singleflight_waits': sum(doc['counters']['singleflight_waits'] for doc in worker_docs),
            'hit_p50_ms': round(_percentile(pure_hit_ms, 50), 3),
            'hit_p99_ms': round(hit_p99, 3),
            'mismatches': [r['idx'] for r in mismatches][:8],
            'occupancy': occupancy,
        }
    )
    expected_records = sum(len(sl) for sl in slices)
    report['checks'] = {
        'corpus_complete': not failures and len(records) == expected_records,
        'byte_identical_to_reference': not mismatches and len(records) == expected_records,
        'hit_rate_ok': hit_rate >= min_hit_rate,
        'herd_collapsed': sentinel_solves == 1,
        'corruption_quarantined': flipped and quarantined >= 1 and occupancy['corrupt'] >= 1,
        # generous lookup+verify bound: a warm hit must not look like a search
        'hit_latency_bounded': bool(pure_hit_ms) and hit_p99 <= report['cold_p50_ms'] * 5 + 50.0,
    }
    report['ok'] = all(report['checks'].values())
    return report


if __name__ == '__main__':
    sys.exit(_worker_main(sys.argv[1:]))
