"""Tiered artifact/solution cache: in-proc LRU → local disk → shared FS.

At fleet scale the PR-13 :class:`~.solution_store.SolutionStore` read path
has three very different latency regimes hiding behind one ``lookup()``:
a program this process already verified (nanoseconds), an entry on the
replica's local disk (sub-millisecond), and an entry on the shared
filesystem every replica mounts (milliseconds to tens of milliseconds on
NFS). :class:`TieredStore` makes the regimes explicit — the canonical
cache hierarchy of the TVM-style compile/serve split (PAPERS.md,
arXiv:1802.04799): solved artifacts flow *down* from the shared tier into
each replica, never the other way up unless the replica itself solved.

Tiers, probed in order on :meth:`lookup`:

1. **mem** — per-process LRU of verified :class:`~.solution_store.StoreHit`
   objects (bounded by ``mem_entries``; ``0`` disables the tier). A mem hit
   costs no I/O and no re-verification — the entry was verified when it
   entered the tier.
2. **local** — a :class:`SolutionStore` directory on replica-local disk.
   Verify-on-read applies exactly as on the shared tier (local disks flip
   bits too); a corrupt local entry quarantines locally and the probe
   falls through to the shared tier.
3. **shared** — the shared-FS tier (``self`` — :class:`TieredStore` *is* a
   :class:`SolutionStore` rooted at the shared directory, so single-flight
   leases, negative caching, gc, and the breaker pair all keep operating
   on the shared tier, where cross-host coordination lives).

A hit at tier *k* **promotes** the entry into every tier above it: a
shared-FS hit copies the raw entry bytes onto local disk (byte-identical
— content-addressed entries are immutable, so a raw copy is exact) and
parks the verified hit in mem. A cold replica joining a warm fleet
therefore serves its first request from the shared tier and every repeat
from mem — no re-solve, no new search (the fleet drill's acceptance
gate, docs/serving.md#replica-fleets).

Writes go through :meth:`publish`: the shared tier is written first (it
is the tier other hosts see — a publish that only landed locally would
be a silent fleet-wide miss), then written through to local + mem.

Per-tier telemetry (docs/telemetry.md): ``store.tier.mem_hits`` /
``store.tier.local_hits`` / ``store.tier.shared_hits`` /
``store.tier.misses`` and ``store.tier.promotes_local`` /
``store.tier.promotes_mem`` / ``store.tier.writethroughs``. The aggregate
``store.hits``/``store.misses`` counters keep their PR-13 meaning (any
tier answered / nothing did), so existing dashboards and budget rules
stay valid.

Wiring: ``DA4ML_STORE_LOCAL_TIER=<dir>`` (optionally with
``DA4ML_STORE_MEM_ENTRIES=<n>``, default 64) upgrades the env-configured
``DA4ML_SOLUTION_STORE`` to a tiered cache everywhere ``resolve_store``
runs — ``solve(store=)``, campaign workers, ``POST /v1/solve`` replicas —
or construct one explicitly via :func:`tiered_at`.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from pathlib import Path

from .. import telemetry
from ..ir.comb import Pipeline
from ..reliability.checkpoint import atomic_write_bytes
from ..reliability.locktrace import make_lock
from .solution_store import SolutionStore, StoreHit

#: default in-proc LRU capacity (entries); DA4ML_STORE_MEM_ENTRIES overrides
DEFAULT_MEM_ENTRIES = 64

_LOCAL_ENV = 'DA4ML_STORE_LOCAL_TIER'
_MEM_ENV = 'DA4ML_STORE_MEM_ENTRIES'


def default_mem_entries() -> int:
    try:
        return int(os.environ.get(_MEM_ENV, '') or DEFAULT_MEM_ENTRIES)
    except ValueError:
        return DEFAULT_MEM_ENTRIES


class TieredStore(SolutionStore):
    """A :class:`SolutionStore` (rooted at the **shared** tier) with a
    local-disk tier and an in-proc LRU layered in front of its read path.

    Every coordination primitive — single-flight leases, negative markers,
    breakers, gc — stays on the shared tier, where it must live for
    cross-host correctness; the upper tiers only ever hold verified copies
    of shared-tier content (or this process's own publishes)."""

    def __init__(
        self,
        shared_root: str | os.PathLike,
        local_root: str | os.PathLike | None = None,
        mem_entries: int | None = None,
        **kw,
    ):
        super().__init__(shared_root, **kw)
        # the local tier never runs single-flight or negative caching of its
        # own (those are shared-tier concerns); it inherits readonly-ness so
        # a snapshotted shared store does not gain a writable shadow
        self.local: SolutionStore | None = (
            SolutionStore(local_root, readonly=self.readonly) if local_root is not None else None
        )
        self.mem_entries = default_mem_entries() if mem_entries is None else int(mem_entries)
        self._mem: 'OrderedDict[str, StoreHit]' = OrderedDict()
        self._mem_lock = make_lock('store.tiered.mem')

    # -- mem tier ------------------------------------------------------------

    def _mem_get(self, key: str) -> StoreHit | None:
        if self.mem_entries <= 0:
            return None
        with self._mem_lock:
            hit = self._mem.get(key)
            if hit is not None:
                self._mem.move_to_end(key)
            return hit

    def _mem_put(self, hit: StoreHit) -> None:
        if self.mem_entries <= 0:
            return
        with self._mem_lock:
            self._mem[hit.key] = hit
            self._mem.move_to_end(hit.key)
            while len(self._mem) > self.mem_entries:
                self._mem.popitem(last=False)
                telemetry.counter('store.tier.mem_evictions').inc()

    # -- promotion -----------------------------------------------------------

    def _promote_to_local(self, key: str) -> None:
        """Copy the verified shared entry's raw bytes onto local disk.

        Byte-identical by construction: entries are content-addressed and
        immutable, so a raw copy of the just-verified file is exact — no
        re-serialization, no fresh timestamps. Best-effort: a failed
        promotion costs the next request a shared-tier read, nothing else."""
        if self.local is None or self.local.readonly:
            return
        try:
            raw = self._entry_path(key).read_bytes()
            atomic_write_bytes(self.local._entry_path(key), raw)
        except OSError:
            return
        telemetry.counter('store.tier.promotes_local').inc()

    # -- read path -----------------------------------------------------------

    def lookup(self, key: str) -> StoreHit | None:
        """Probe mem → local → shared; promote upward on a hit. Aggregate
        ``store.hits``/``store.misses`` accounting is preserved."""
        hit = self._mem_get(key)
        if hit is not None:
            telemetry.counter('store.tier.mem_hits').inc()
            telemetry.counter('store.hits').inc()
            return hit
        if self.local is not None:
            hit = self.local._read(key)
            if hit is not None:
                telemetry.counter('store.tier.local_hits').inc()
                telemetry.counter('store.hits').inc()
                self._mem_put(hit)
                telemetry.counter('store.tier.promotes_mem').inc()
                return hit
        hit = super().lookup(key)  # shared tier: the accounted probe
        if hit is not None:
            telemetry.counter('store.tier.shared_hits').inc()
            self._promote_to_local(key)
            self._mem_put(hit)
            telemetry.counter('store.tier.promotes_mem').inc()
        else:
            telemetry.counter('store.tier.misses').inc()
        return hit

    # -- write path ----------------------------------------------------------

    def publish(self, key: str, pipeline: Pipeline, meta: dict | None = None) -> bool:
        """Publish to the shared tier, then write through to local + mem.

        The write-through copies the exact bytes that landed on the shared
        tier (same byte-identity contract as promotion)."""
        ok = super().publish(key, pipeline, meta=meta)
        if ok:
            self._promote_to_local(key)
            telemetry.counter('store.tier.writethroughs').inc()
            hit = StoreHit(key=key, pipeline=pipeline, doc={'key': key, 'cost': float(pipeline.cost), **(meta or {})})
            self._mem_put(hit)
        return ok

    # -- introspection -------------------------------------------------------

    def tier_occupancy(self) -> dict:
        """Per-tier occupancy for /statusz and ``da4ml-tpu cache stats``."""
        with self._mem_lock:
            mem = len(self._mem)
        return {
            'mem': {'entries': mem, 'cap': self.mem_entries},
            'local': self.local.occupancy() if self.local is not None else None,
            'shared': super().occupancy(),
        }

    def occupancy(self) -> dict:
        out = super().occupancy()
        out['tiers'] = self.tier_occupancy()
        return out


def tiered_at(
    shared_root: str | os.PathLike,
    local_root: str | os.PathLike | None = None,
    mem_entries: int | None = None,
    **kw,
) -> TieredStore:
    """Process-wide :class:`TieredStore` per (shared, local) directory pair
    (the tiered twin of :func:`~.solution_store.store_at`)."""
    from .solution_store import _stores, _stores_lock

    shared = str(Path(shared_root).expanduser().resolve())
    local = str(Path(local_root).expanduser().resolve()) if local_root is not None else None
    key = f'{shared}|tier:{local}'
    with _stores_lock:
        store = _stores.get(key)
        if not isinstance(store, TieredStore):
            _stores[key] = store = TieredStore(shared, local, mem_entries=mem_entries, **kw)
        return store


def local_tier_env() -> str | None:
    """The ``DA4ML_STORE_LOCAL_TIER`` directory, or None when unset."""
    env = os.environ.get(_LOCAL_ENV, '').strip()
    return env or None


__all__ = ['DEFAULT_MEM_ENTRIES', 'TieredStore', 'default_mem_entries', 'local_tier_env', 'tiered_at']
