"""The solver-as-a-service plane behind ``POST /v1/solve``.

Reuses the serve plane's admission machinery (``serve.batching``): each
solve request enters the same bounded row-counted queue under the
``deadline-edf`` shed policy, so an overloaded solver sheds with 429 +
``Retry-After`` and expired requests are dropped with 504 *before* any
search runs. Service order is earliest-deadline-first.

The hit path is bounded by lookup + verify — a warm store answers in
milliseconds regardless of how expensive the original search was. Cold
misses run the real solve through the store's single-flight, so a
thundering herd of identical kernels produces exactly one search no matter
how many service workers (or hosts) share the store directory.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .. import telemetry
from ..reliability.errors import InvalidInputError, SolveTimeout
from ..serve.batching import AdmissionQueue, DeadlineExpired, Draining, InferRequest, ServeRejected
from .solution_store import StoreNegativeEntry, resolve_store, store_key

#: hard per-request kernel size ceiling (entries): parse-side bound so one
#: fat request cannot monopolize the solver plane
MAX_KERNEL_ENTRIES = 1 << 20


class SolveUnavailable(ServeRejected):
    """The key is negative-cached: a recent solve failed terminally on
    every backend (HTTP 503 + Retry-After from the marker's TTL)."""

    http_status = 503


class SolveRequest(InferRequest):
    """One admitted solve request: the kernel rides in ``x`` (row count =
    kernel rows, the axis the search cost scales with), plus the quality
    knob."""

    __slots__ = ('quality',)

    def __init__(self, kernel: np.ndarray, deadline_s: float | None, quality=None):
        super().__init__(kernel, deadline_s)
        self.quality = quality


class SolveService:
    """EDF-admitted solve workers over one (optional) solution store."""

    def __init__(
        self,
        store=None,
        backend: str = 'auto',
        queue_cap_rows: int = 256,
        workers: int = 1,
        default_deadline_s: float | None = 30.0,
        shed_policy: str = 'deadline-edf',
        solver_options: dict | None = None,
    ):
        self.store = resolve_store(store)
        self.backend = backend
        self.solver_options = dict(solver_options or {})
        self.default_deadline_s = default_deadline_s
        self.queue = AdmissionQueue(queue_cap_rows, policy=shed_policy)
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._worker_loop, name=f'da4ml-solve-svc-{i}', daemon=True)
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    # -- admission -----------------------------------------------------------

    def submit(self, kernel, quality=None, deadline_s: float | None = None) -> SolveRequest:
        """Validate + admit one solve request; raises the serve taxonomy
        (400/429/503) at admission time, 504 at dispatch time."""
        if self._stop.is_set():
            raise Draining('solve service is draining')
        try:
            k = np.asarray(kernel, dtype=np.float64)
        except (ValueError, TypeError) as e:
            raise InvalidInputError(f'kernel is not a numeric matrix: {e}') from e
        if k.ndim != 2 or k.shape[0] == 0 or k.shape[1] == 0:
            raise InvalidInputError(f'kernel must be a non-empty 2D matrix, got shape {k.shape}')
        if k.size > MAX_KERNEL_ENTRIES:
            raise InvalidInputError(f'kernel of {k.size} entries exceeds the {MAX_KERNEL_ENTRIES} ceiling')
        if not np.all(np.isfinite(k)):
            raise InvalidInputError('kernel contains non-finite (NaN/inf) values')
        req = SolveRequest(k, deadline_s if deadline_s is not None else self.default_deadline_s, quality)
        tb = telemetry.current_trace()
        if tb is not None:
            # adopt the submitting thread's trace context so the worker's
            # store-tier spans join the request's fleet-wide trace
            req.trace_id = tb[0]
            cur = telemetry.current_span()
            req.parent_span_id = cur.span_id if cur is not None else tb[1]
        try:
            self.queue.push(req)
        except ServeRejected:
            telemetry.counter('serve.solve_shed').inc()
            raise
        telemetry.counter('serve.solve_requests').inc()
        return req

    # -- service -------------------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            # max_rows=1: take one request per round (the first is always
            # taken) so multiple service workers solve distinct keys in
            # parallel while the queue keeps EDF order
            batch = self.queue.take_batch(max_rows=1, window_s=0.0, stop=self._stop)
            if not batch:
                if self._stop.is_set():
                    return
                continue
            for req in batch:
                if req.expired():
                    telemetry.counter('serve.solve_expired').inc()
                    req.set_error(DeadlineExpired(f'solve request {req.id} expired before dispatch'))
                    continue
                try:
                    if req.trace_id is not None:
                        # rebind the request's trace on this worker thread so
                        # the store-tier spans carry the same trace id
                        with telemetry.bind_trace(req.trace_id, req.parent_span_id):
                            doc = self._solve_one(req)
                    else:
                        doc = self._solve_one(req)
                    req.set_result(doc, served_by=f'solve[{self.backend}]')
                except BaseException as e:  # noqa: BLE001 - resolved into the request
                    req.set_error(e)

    def _solve_one(self, req: SolveRequest) -> dict:
        from ..cmvm.api import solve
        from ..reliability.orchestrator import canonical_backend
        from ..reliability.report import SolveReport

        t0 = time.perf_counter()
        remaining = None if req.deadline is None else max(req.deadline - time.monotonic(), 0.01)
        kw = dict(self.solver_options)
        if req.quality is not None:
            kw['quality'] = req.quality
        key = store_key(req.x, self.backend, kw)
        canon = canonical_backend(self.backend)
        info: dict = {}
        rep = SolveReport()

        def cold():
            # store=False: solve_through IS the store path; the cold branch
            # must not recurse into another lookup
            return solve(req.x, backend=self.backend, store=False, deadline=remaining, report=rep, **kw)

        try:
            if self.store is not None:
                pipe = self.store.solve_through(
                    key,
                    cold,
                    meta={'backend': canon},
                    deadline_s=remaining,
                    info=info,
                    # a chain-degraded answer must not be published under
                    # this requested-backend key (determinism is per-backend)
                    publish_ok=lambda: rep.backend_used in (None, canon),
                )
            else:
                pipe = cold()
                info['source'] = 'solve'
        except StoreNegativeEntry as e:
            raise SolveUnavailable(str(e), retry_after_s=e.retry_after_s) from e
        except SolveTimeout as e:
            telemetry.counter('serve.solve_expired').inc()
            raise DeadlineExpired(f'solve request {req.id}: {e}') from e
        source = info.get('source', 'solve')
        telemetry.counter(f'serve.solve_{"hits" if source == "store" else "misses"}').inc()
        return {
            'key': key,
            'source': source,
            'cost': float(pipe.cost),
            'backend': info.get('backend') or self.backend,
            'solve_ms': round((time.perf_counter() - t0) * 1e3, 3),
            'pipeline': pipe.to_dict(),
        }

    # -- lifecycle -----------------------------------------------------------

    def close(self, grace_s: float = 10.0) -> None:
        """Drain: stop admitting, serve everything accepted, then stop the
        workers (same contract as the serve engine)."""
        self._stop.set()
        deadline = time.monotonic() + grace_s
        while self.queue.depth_requests() and time.monotonic() < deadline:
            time.sleep(0.02)
        self.queue.flush(lambda: Draining('solve service stopped'))
        for t in self._threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))
