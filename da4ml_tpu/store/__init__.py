"""Global content-addressed solution store (docs/store.md).

``SolutionStore`` maps full kernel digest + canonical solver options to a
solved DAIS program with verify-on-read, single-flighted cold misses,
negative caching, and breaker-guarded degradation. ``cmvm.api.solve``
consults it via ``store=`` / ``DA4ML_SOLUTION_STORE``; campaigns publish
into it; the serve plane exposes it as ``POST /v1/solve``. ``TieredStore``
(``DA4ML_STORE_LOCAL_TIER``) layers an in-proc LRU and a local-disk tier
in front of the shared directory so fleet replicas warm from the shared
tier instead of re-solving.
"""

from .service import SolveService
from .solution_store import (
    SolutionStore,
    StoreEntryCorrupt,
    StoreHit,
    StoreNegativeEntry,
    canonical_solve_opts,
    default_store,
    reset_store_registry,
    resolve_store,
    store_at,
    store_health,
    store_key,
    store_status,
)
from .tiered import TieredStore, tiered_at

__all__ = [
    'SolutionStore',
    'SolveService',
    'TieredStore',
    'StoreEntryCorrupt',
    'StoreHit',
    'StoreNegativeEntry',
    'canonical_solve_opts',
    'default_store',
    'reset_store_registry',
    'resolve_store',
    'store_at',
    'store_health',
    'store_key',
    'store_status',
    'tiered_at',
]
