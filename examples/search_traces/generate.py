#!/usr/bin/env python
"""Regenerate the committed search-trace corpus + trained ranker.

Runs a deterministic beam solve campaign with ``DA4ML_SEARCH_TRACE_DIR``
armed, consolidates the per-process trace files into one canonical
``trace_corpus.jsonl`` (records sorted, so the file is byte-stable), and
fits the committed ``ranker.json`` from it (search/train.py — closed-form,
no RNG). Run from the repo root::

    JAX_PLATFORMS=cpu python examples/search_traces/generate.py
"""

from __future__ import annotations

import json
import os
import tempfile

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
SEED = 20260804


def main() -> int:
    with tempfile.TemporaryDirectory() as td:
        os.environ['DA4ML_SEARCH_TRACE_DIR'] = td
        from da4ml_tpu.cmvm import SearchSpec
        from da4ml_tpu.cmvm.jax_search import solve_jax_many
        from da4ml_tpu.cmvm.search.trace import load_trace_dir
        from da4ml_tpu.cmvm.search.train import train_from_dir

        rng = np.random.default_rng(SEED)
        kernels, lats = [], []
        for dim, bits in [(8, 4), (10, 4), (12, 4), (12, 3), (14, 4), (16, 4), (16, 3)]:
            mag = rng.integers(0, 2**bits, (dim, dim)).astype(np.float64)
            kernels.append(mag * rng.choice([-1.0, 1.0], (dim, dim)))
            # staggered input latencies so the latency_skew feature is live
            lats.append([float(v) for v in rng.integers(0, 3, dim)])
        # deep fork-everything spec: the training corpus wants feature
        # variance (depth_remaining, novelty, skew), not the bounded-wall
        # preset the ranker will later steer
        spec = SearchSpec(beam=4, depth=3, focus=0, include_host=False)
        solve_jax_many(kernels, latencies_list=lats, quality=spec)
        del os.environ['DA4ML_SEARCH_TRACE_DIR']

        records = load_trace_dir(td)
        records.sort(key=lambda r: json.dumps(r, sort_keys=True))
        out = os.path.join(HERE, 'trace_corpus.jsonl')
        with open(out, 'w') as fh:
            for r in records:
                fh.write(json.dumps(r, sort_keys=True) + '\n')
        print(f'{len(records)} records -> {out}')

    ranker = train_from_dir(HERE)
    ranker.save(os.path.join(HERE, 'ranker.json'))
    print(f'trained ranker -> {os.path.join(HERE, "ranker.json")}')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
