"""Quantized Keras model -> DAIS program, no manual input precision.

Builds a QKeras-style model from the in-tree compatible classes, saves and
reloads it through .keras serialization (the classes register under the
'qkeras' package), traces it with the quantizer-aware front-end, and checks
the DAIS program is bit-exact against model.predict.

Run: python examples/02_quantized_keras_convert.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo checkout use

import numpy as np

import keras

from da4ml_tpu.converter import trace_model
from da4ml_tpu.converter.qkeras_compat import QActivation, QDense, quantized_bits, quantized_relu
from da4ml_tpu.trace import HWConfig, comb_trace

rng = np.random.default_rng(1)
model = keras.Sequential(
    [
        keras.layers.Input((10,)),
        QActivation(quantized_bits(6, 2)),  # records the input format
        QDense(16, kernel_quantizer=quantized_bits(6, 2), bias_quantizer=quantized_bits(6, 2),
               activation=quantized_relu(6, 3)),  # fmt: skip
        QDense(4, kernel_quantizer=quantized_bits(5, 1), bias_quantizer=quantized_bits(5, 1)),
    ]
)
for w in model.weights:
    w.assign(rng.uniform(-2, 2, w.shape))

inp, out = trace_model(model, HWConfig(1, -1, -1), {'hard_dc': 2})
comb = comb_trace(inp, out)

# test data on the model's own input grid
eps, span = 2.0**-3, 2.0**2
data = rng.integers(-span / eps + 1, span / eps, (512, 10)).astype(np.float64) * eps
golden = np.asarray(model.predict(data.astype(np.float32), verbose=0), np.float64)
got = comb.predict(data)
assert np.array_equal(got, golden), 'DAIS program must match model.predict bit-exactly'
print(f'bit-exact: {got.shape[0]} samples, {len(comb.ops)} ops, est. {comb.cost:.0f} LUTs')
