"""Batched CMVM search on the accelerator, sharded over a device mesh.

Solves a batch of random kernels with the device search (every
matrix x decomposition-depth candidate as one lane batch), checks
exactness and decision-identity against the host solver, and repeats with
the lane axis sharded over all visible devices.

On a CPU-only host, run with a virtual mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/03_tpu_batch_solve.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo checkout use

import time

import numpy as np

import jax

from da4ml_tpu.cmvm import solve
from da4ml_tpu.cmvm.jax_search import solve_jax_many
from da4ml_tpu.parallel import default_mesh

import os

rng = np.random.default_rng(7)
# batch size: 16 shows off throughput; the test gallery shrinks it via env
# (CPU-XLA executes the search ~100x slower than a TPU chip)
N = int(os.environ.get('DA4ML_EXAMPLE_N', '16'))
kernels = [(rng.integers(0, 16, (16, 16)) * rng.choice([-1.0, 1.0], (16, 16))).astype(np.float64) for _ in range(N)]

solve_jax_many(kernels[:2])  # warm the XLA compile cache
t0 = time.perf_counter()
sols = solve_jax_many(kernels)
rate = len(kernels) / (time.perf_counter() - t0)

host = [solve(k, backend='auto') for k in kernels]
identical = sum(int(float(a.cost) == float(b.cost)) for a, b in zip(sols, host))
for k, s in zip(kernels, sols):
    assert np.array_equal(np.asarray(s.kernel, np.float64), k)
print(f'{jax.default_backend()}: {rate:.1f} matrices/s, cost identical to host on {identical}/{len(kernels)}')

mesh = default_mesh('lanes')
sols_sharded = solve_jax_many(kernels, mesh=mesh)
assert all(float(a.cost) == float(b.cost) for a, b in zip(sols, sols_sharded))
print(f'mesh({mesh.devices.size} devices): sharded sweep reproduces the same solutions')
