"""JEDI-linear-style MLP -> shift-add network -> Verilog project.

The end-to-end functional flow: symbolic fixed-point tracing, CMVM
optimization of every constant matmul, bit-exact software inference, and a
synthesizable RTL project with timing constraints and build scripts.

Run: python examples/01_mlp_to_verilog.py [outdir]
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo checkout use

import numpy as np

from da4ml_tpu.codegen import VerilogModel
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

rng = np.random.default_rng(0)

# 16 -> 32 -> 32 -> 5 MLP, 6-bit weights, quantized activations
inp = FixedVariableArrayInput(16, hwconf=HWConfig(1, -1, -1), solver_options={'backend': 'auto'})
x = inp.quantize(np.ones(16), np.full(16, 3), np.full(16, 2))  # input format: s1.3.2
for width in (32, 32):
    w = rng.integers(-32, 32, (x.shape[0], width)).astype(np.float64)
    x = (x @ w).relu(i=np.full(width, 7), f=np.full(width, 2))
w_out = rng.integers(-32, 32, (x.shape[0], 5)).astype(np.float64)
out = x @ w_out

comb = comb_trace(inp, out)
print(f'traced: {comb.shape[0]} inputs -> {comb.shape[1]} outputs, '
      f'{len(comb.ops)} ops, est. {comb.cost:.0f} LUTs, latency {max(comb.latency):.0f}')  # fmt: skip

# bit-exact software inference (native C++ interpreter)
data = rng.uniform(-8, 8, (1024, 16))
y = comb.predict(data)
assert np.array_equal(y, comb.predict(data, backend='numpy'))
print('predict: native == numpy, bit-exact')

outdir = sys.argv[1] if len(sys.argv) > 1 else '/tmp/da4ml_example_mlp'
model = VerilogModel(comb, 'jedi_mlp', outdir, latency_cutoff=5.0)
model.write()
print(f'Verilog project written to {outdir} ({len(model.solution.stages)} pipeline stages)')
