import sys

from ._cli import main

sys.exit(main())
