"""CMVM core: the greedy CSE loop and adder-tree emission.

``cmvm`` runs the iterative subexpression elimination until the frequency map
drains; ``to_solution`` turns the residual sparse expressions into balanced
shift-add reduction trees per output (min-heap keyed on latency, so the trees
are latency-optimal), producing a ``CombLogic``.

Behavioral parity: reference src/da4ml/_binary/cmvm/cmvm_core.cc.
"""

from __future__ import annotations

import heapq
from math import log2

import numpy as np
from numpy.typing import NDArray

from ..ir.comb import CombLogic
from ..ir.types import Op, QInterval, qint_add
from .cost import cost_add
from .heuristics import select_pair
from .state import DAState, create_state, to_shift, to_sign, update_state


def cmvm(
    kernel: NDArray,
    method: str,
    qintervals: list[QInterval] | None = None,
    inp_latencies: list[float] | None = None,
    adder_size: int = -1,
    carry_size: int = -1,
) -> DAState:
    kernel = np.asarray(kernel, dtype=np.float64)
    n_in = kernel.shape[0]
    if not qintervals:
        qintervals = [QInterval(-128.0, 127.0, 1.0)] * n_in
    if not inp_latencies:
        inp_latencies = [0.0] * n_in

    state = create_state(kernel, qintervals, inp_latencies, no_stat_init=method == 'dummy')
    while state.freq_stat:
        pair = select_pair(state, method)
        if pair.id0 == -1 or pair.id1 == -1:
            break
        update_state(state, pair, adder_size, carry_size)
    return state


def _left_align(qint: QInterval, shift: int) -> int:
    return int(log2(max(abs(qint.max + qint.step), abs(qint.min)))) + shift


def to_solution(state: DAState, adder_size: int, carry_size: int) -> CombLogic:
    """Emit the balanced reduction trees for each output column (cmvm_core.cc:89-225)."""
    ops = list(state.ops)
    n_out = state.n_out
    n_expr = len(state.expr)

    out_idxs: list[int] = []
    out_shifts: list[int] = []
    out_negs: list[int] = []
    inp_shifts = [int(v) for v in state.shift0]
    out_shifts_base = [int(v) for v in state.shift1]

    _global_id = len(ops)

    for i_out in range(n_out):
        idx: list[int] = []
        shifts: list[int] = []
        subs: list[int] = []
        for i_in in range(n_expr):
            for v in state.expr[i_in][i_out]:
                idx.append(i_in)
                shifts.append(to_shift(v))
                subs.append(1 if to_sign(v) == -1 else 0)

        if len(idx) == 1:
            out_shifts.append(out_shifts_base[i_out] + shifts[0])
            out_idxs.append(idx[0])
            out_negs.append(subs[0])
            continue
        if not idx:
            out_idxs.append(-1)
            out_shifts.append(out_shifts_base[i_out])
            out_negs.append(0)
            continue

        # heap entries ordered by (lat, sub, left_align, qmin, qmax, qstep, id, shift)
        heap = []
        for k in range(len(idx)):
            qint = ops[idx[k]].qint
            lat = ops[idx[k]].latency
            heap.append((lat, subs[k], _left_align(qint, shifts[k]), qint.min, qint.max, qint.step, idx[k], shifts[k]))
        heapq.heapify(heap)

        while len(heap) > 1:
            lat0, sub0, _, qmin0, qmax0, qstep0, id0, shift0 = heapq.heappop(heap)
            lat1, sub1, _, qmin1, qmax1, qstep1, id1, shift1 = heapq.heappop(heap)
            qint0 = QInterval(qmin0, qmax0, qstep0)
            qint1 = QInterval(qmin1, qmax1, qstep1)

            if sub0:
                s = shift0 - shift1
                qint = qint_add(qint1, qint0, s, bool(sub1), bool(sub0))
                dlat, dcost = cost_add(qint1, qint0, s, bool(1 ^ sub1), adder_size, carry_size)
                lat = max(lat0, lat1) + dlat
                op = Op(id1, id0, 1 ^ sub1, s, qint, lat, dcost)
                result_shift = shift1
            else:
                s = shift1 - shift0
                qint = qint_add(qint0, qint1, s, bool(sub0), bool(sub1))
                dlat, dcost = cost_add(qint0, qint1, s, bool(sub1), adder_size, carry_size)
                lat = max(lat0, lat1) + dlat
                op = Op(id0, id1, sub1, s, qint, lat, dcost)
                result_shift = shift0

            heapq.heappush(
                heap,
                (op.latency, sub0 & sub1, _left_align(qint, result_shift), qint.min, qint.max, qint.step, _global_id, result_shift),
            )
            ops.append(op)
            _global_id += 1

        final = heap[0]
        out_idxs.append(_global_id - 1)
        out_negs.append(final[1])
        out_shifts.append(out_shifts_base[i_out] + final[7])

    return CombLogic(
        shape=(state.kernel.shape[0], n_out),
        inp_shifts=inp_shifts,
        out_idxs=out_idxs,
        out_shifts=out_shifts,
        out_negs=[bool(v) for v in out_negs],
        ops=ops,
        carry_size=carry_size,
        adder_size=adder_size,
    )


def solve_single(
    kernel: NDArray,
    method: str,
    qintervals: list[QInterval] | None = None,
    latencies: list[float] | None = None,
    adder_size: int = -1,
    carry_size: int = -1,
) -> CombLogic:
    state = cmvm(kernel, method, qintervals, latencies, adder_size, carry_size)
    return to_solution(state, adder_size, carry_size)
