"""Pair-selection heuristics for the greedy CSE loop.

All heuristics scan the frequency map in the reference's sorted Pair order
(id1, id0, sub, shift) with >=-argmax, so ties resolve identically to the
reference's flat-vector scan (indexers.cc).

Methods: mc (most common), mc-dc / mc-pdc (latency-difference penalized),
wmc (bit-overlap weighted), wmc-dc / wmc-pdc.
"""

from __future__ import annotations

from .cost import overlap_and_accum
from .state import DAState, Pair

_NONE = Pair(-1, -1, False, 0)


def _sorted_items(state: DAState):
    return sorted(state.freq_stat.items(), key=lambda kv: kv[0].sort_key)


def idx_mc(state: DAState) -> Pair:
    best, max_freq = _NONE, 0
    for p, c in _sorted_items(state):
        if c >= max_freq:
            max_freq, best = c, p
    return best


def idx_mc_dc(state: DAState, absolute: bool) -> Pair:
    best = _NONE
    factor = 1e9
    max_score = 0.0 if absolute else float('-inf')
    for p, c in _sorted_items(state):
        lat0 = state.ops[p.id0].latency
        lat1 = state.ops[p.id1].latency
        score = c - factor * abs(lat0 - lat1)
        if score >= max_score:
            max_score, best = score, p
    return best


def idx_wmc(state: DAState) -> Pair:
    best, max_score = _NONE, 0
    for p, c in _sorted_items(state):
        n_overlap, _ = overlap_and_accum(state.ops[p.id0].qint, state.ops[p.id1].qint)
        score = c * n_overlap
        if score >= max_score:
            max_score, best = score, p
    return best


def idx_wmc_dc(state: DAState, absolute: bool) -> Pair:
    best = _NONE
    max_score = 0.0 if absolute else float('-inf')
    for p, c in _sorted_items(state):
        n_overlap, _ = overlap_and_accum(state.ops[p.id0].qint, state.ops[p.id1].qint)
        lat0 = state.ops[p.id0].latency
        lat1 = state.ops[p.id1].latency
        score = c * n_overlap - 256 * abs(lat0 - lat1)
        if score >= max_score:
            max_score, best = score, p
    return best


def select_pair(state: DAState, method: str) -> Pair:
    if method == 'mc':
        return idx_mc(state)
    if method == 'mc-dc':
        return idx_mc_dc(state, True)
    if method == 'mc-pdc':
        return idx_mc_dc(state, False)
    if method == 'wmc':
        return idx_wmc(state)
    if method == 'wmc-dc':
        return idx_wmc_dc(state, True)
    if method == 'wmc-pdc':
        return idx_wmc_dc(state, False)
    if method == 'dummy':
        return _NONE
    raise ValueError(f'Unknown method: {method}')
