"""Hardware cost/latency model for shift-add operations.

``cost_add`` returns (latency_delta, cost) of one adder: bits of accumulation
``n = k + i + f`` of the aligned sum, giving latency ``ceil(n/carry_size)``
(carry-chain delay) and cost ``ceil(n/adder_size)`` (LUT estimate). Size -1
means "one unit regardless" (both -1) / "unbounded" (single -1).

Behavioral parity: reference src/da4ml/_binary/cmvm/state_opr.cc:31-67 and
indexers.cc:36-56 (``overlap_and_accum``).
"""

from __future__ import annotations

from math import ceil, log2

from ..ir.types import QInterval


def cost_add(q0: QInterval, q1: QInterval, shift: int, sub: bool, adder_size: int, carry_size: int) -> tuple[float, float]:
    if adder_size < 0 and carry_size < 0:
        return 1.0, 1.0
    if adder_size < 0:
        adder_size = 65535
    if carry_size < 0:
        carry_size = 65535

    min0, max0, step0 = q0
    min1, max1, step1 = q1
    if sub:
        min1, max1 = max1, min1
    sf = 2.0**shift
    min1, max1, step1 = min1 * sf, max1 * sf, step1 * sf
    max0 += step0
    max1 += step1

    f = -log2(max(step0, step1))
    i = ceil(log2(max(abs(min0), abs(min1), abs(max0), abs(max1))))
    k = 1 if (q0.min < 0 or q1.min < 0) else 0
    n_accum = k + i + f
    return float(ceil(n_accum / carry_size)), float(ceil(n_accum / adder_size))


def _iceil_log2(x: float) -> int:
    return int(ceil(log2(x))) if x > 0 else 0


def overlap_and_accum(q0: QInterval, q1: QInterval) -> tuple[int, int]:
    """(n_overlap, n_accum) bit counts used by the wmc scoring heuristic."""
    min0, max0, step0 = q0
    min1, max1, step1 = q1
    max0 += step0
    max1 += step1
    f = -_iceil_log2(max(step0, step1))
    i_high = _iceil_log2(max(abs(min0), abs(min1), abs(max0), abs(max1)))
    i_low = _iceil_log2(min(max(abs(min0), abs(max0)), max(abs(min1), abs(max1))))
    k = 1 if (q0.min < 0 or q1.min < 0) else 0
    return k + i_low + f, k + i_high + f
