"""Stage-1 graph decomposition: W = W1 @ W2 via a Prim MST over columns.

Columns of the (centered) kernel are graph vertices plus a zero root; the
edge weight between two columns is the CSD Hamming weight of their difference
or sum (whichever is smaller). The MST edges become the columns of W1; W2
records how they recombine into the original columns.

Behavioral parity: reference src/da4ml/_binary/cmvm/mat_decompose.cc and
docs/cmvm.md:9-17.
"""

from __future__ import annotations

import numpy as np
from numpy.typing import NDArray

from .csd import center, int_arr_to_csd

_INF = np.int64(2**62)


def prim_mst_dc(cost_mat: NDArray[np.int64], dc: int) -> NDArray[np.int32]:
    """Prim's MST from root 0, optionally latency(depth)-constrained by ``dc``.

    Returns edge list [(parent, child)] in insertion order.
    Parity: mat_decompose.cc:6-60.
    """
    n = cost_mat.shape[0]
    lat_mat = np.ceil(np.log2(np.maximum(cost_mat, 1).astype(np.float64)))
    parent = np.full(n, -2, dtype=np.int64)
    parent[0] = -1
    latency = np.zeros(n, dtype=np.int64)
    mapping = np.empty((n - 1, 2), dtype=np.int32)

    _dc = -1.0
    if dc >= 0:
        max_cost0 = float(cost_mat[0].max())
        _dc = (2.0**dc - 1) + np.ceil(np.log2(max_cost0 + 1e-32))

    for n_impl in range(1, n):
        impl = np.flatnonzero(parent != -2)
        not_impl = np.flatnonzero(parent == -2)
        sub = cost_mat[np.ix_(not_impl, impl)].copy()
        if dc >= 0:
            max_lat = np.maximum(lat_mat[np.ix_(not_impl, impl)], latency[impl][None, :]) + 1
            sub = np.where(max_lat > _dc, _INF // 2, sub)
        flat = int(np.argmin(sub))
        bi, bj = divmod(flat, len(impl))
        i, j = int(not_impl[bi]), int(impl[bj])
        parent[i] = j
        mapping[n_impl - 1, 0] = j
        mapping[n_impl - 1, 1] = i
        latency[i] = int(max(lat_mat[i, j], latency[j]) + 1)
    return mapping


def kernel_decompose(kernel: NDArray, dc: int) -> tuple[NDArray[np.float64], NDArray[np.float64]]:
    """Decompose ``kernel`` into (m0, m1) with ``m0 @ m1 == kernel``.

    ``dc == -1`` returns the identity split. Parity: mat_decompose.cc:62-137.
    """
    kernel = np.array(kernel, dtype=np.float64)
    centered, shift0, shift1 = center(kernel)
    scale0 = 2.0 ** shift0.astype(np.float64)
    scale1 = 2.0 ** shift1.astype(np.float64)
    n_in, n_out = centered.shape

    if dc == -1:
        return centered * scale0[:, None], np.eye(n_out) * scale1

    # augmented with zero root column at index 0
    mat_aug = np.zeros((n_in, n_out + 1))
    mat_aug[:, 1:] = centered

    diff0 = mat_aug[:, :, None] - mat_aug[:, None, :]
    diff1 = mat_aug[:, :, None] + mat_aug[:, None, :]
    csd0 = int_arr_to_csd(diff0.astype(np.int64))
    csd1 = int_arr_to_csd(diff1.astype(np.int64))
    dist0 = (csd0 != 0).sum(axis=(0, 3)).astype(np.int64)
    dist1 = (csd1 != 0).sum(axis=(0, 3)).astype(np.int64)
    sign_arr = np.where(dist1 - dist0 < 0, -1, 1).astype(np.int64)
    dist = np.minimum(dist0, dist1)

    mapping = prim_mst_dc(dist, dc)

    m0 = np.zeros((n_in, n_out))
    m1 = np.zeros((n_out, n_out))
    cnt = 0
    for k in range(mapping.shape[0]):
        _from, _to = int(mapping[k, 0]), int(mapping[k, 1])
        col0 = mat_aug[:, _to] - mat_aug[:, _from] * sign_arr[_to, _from]
        if _from != 0:
            col1 = m1[:, _from - 1] * sign_arr[_to, _from]
        else:
            col1 = np.zeros(n_out)
        if np.any(col0 != 0):
            col1 = col1.copy()
            col1[cnt] = 1.0
            m0[:, cnt] = col0
            cnt += 1
        m1[:, _to - 1] = col1
    return m0 * scale0[:, None], m1 * scale1
