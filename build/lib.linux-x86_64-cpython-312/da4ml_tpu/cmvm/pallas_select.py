"""Pallas TPU kernel for the CSE pair-selection step.

The XLA path of the device search materializes, per greedy iteration, the
full candidate tensor ``[2, B, P, P]`` (counts, scores, masks) in HBM — at
P≈128 that is hundreds of MB of traffic per iteration across a lane batch.
This kernel fuses pair counting (MXU dots), scoring, masking, and the
argmax into one VMEM-resident program per lane: HBM sees only the digit
tensor going in and two scalars coming out.

Per lane (grid cell):
  inputs   e    [P, O*B]    f32  — digit tensor, bit-major within output
           sh   [B, P, O*B] f32  — e shifted by s along the bit axis
           nov  [P, P]      f32  — pairwise overlap weights
           dlat [P, P]      f32  — pairwise latency imbalance
           coef [1, 4]      f32  — (w_mc, w_ov, penalty, absolute) from the
                                   per-lane heuristic code
  output   out  [1, 2]      i32  — (flat candidate index, any_valid)

Flat index layout matches the XLA path (``sub``-major, then shift, then
(i, j) row-major), and the scan order (sub outer, s inner, strict ``>``
update, first-index tie-break within a slice) reproduces its tie-breaking
exactly, so both implementations are decision-identical.

Selection is enabled with ``DA4ML_JAX_SELECT=pallas`` (interpret mode is
used automatically off-TPU). Reference for the selection semantics:
src/da4ml/_binary/cmvm/indexers.cc of calad0i/da4ml.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is unavailable on some CPU-only builds; interpret mode suffices
    from jax.experimental.pallas import tpu as pltpu

    _SMEM = pltpu.SMEM
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _SMEM = _VMEM = None


# Per-core VMEM is ~16 MiB on current TPUs; the kernel keeps every operand
# resident (no blocking), so refuse shape classes whose working set cannot
# fit with headroom for the dot-general accumulators.
VMEM_BUDGET_BYTES = 8 * 1024 * 1024


def vmem_footprint_bytes(P: int, O: int, B: int) -> int:
    """Resident f32 working set of the fused select kernel for one lane."""
    OB = O * B
    sh = B * P * OB * 4  # shifted digit stack — the dominant term
    e = P * OB * 4
    pairs = 2 * P * P * 4  # nov + dlat
    scratch = 4 * P * P * 4  # dot outputs + score/valid temporaries
    return sh + e + pairs + scratch


def fits_vmem(P: int, O: int, B: int, budget: int = VMEM_BUDGET_BYTES) -> bool:
    """Whether the fused kernel's working set fits in VMEM for this class.

    The staged search grows P past 128 where ``sh`` alone can exceed the
    budget (e.g. P=256, O=64, B=16 -> 16 MiB for ``sh``); callers must fall
    back to the XLA select path when this returns False.
    """
    return vmem_footprint_bytes(P, O, B) <= budget


def _vspec():
    return pl.BlockSpec(memory_space=_VMEM) if _VMEM is not None else pl.BlockSpec()


def _sspec():
    return pl.BlockSpec(memory_space=_SMEM) if _SMEM is not None else pl.BlockSpec()


@lru_cache(maxsize=64)
def make_select(P: int, O: int, B: int, interpret: bool = False):
    """Build the fused select function for one shape class.

    Returns ``select(e, sh, nov, dlat, coef) -> (flat, any_valid)`` operating
    on a single lane; `jax.vmap` lifts it to the lane batch (pallas adds a
    grid axis).
    """
    OB = O * B

    def kernel(e_ref, sh_ref, nov_ref, dlat_ref, coef_ref, out_ref):
        e = e_ref[...]  # [P, OB]
        ea = jnp.abs(e)
        nov = nov_ref[...]  # [P, P]
        dl = dlat_ref[...]
        w_mc = coef_ref[0, 0]
        w_ov = coef_ref[0, 1]
        pen = coef_ref[0, 2]
        absolute = coef_ref[0, 3]

        row = jax.lax.broadcasted_iota(jnp.int32, (P, P), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (P, P), 1)
        iota2 = row * P + col
        upper = row < col
        big = jnp.int32(2**30)
        neg_inf = jnp.float32(-jnp.inf)

        weight = w_mc + nov * w_ov
        pen_dl = pen * dl

        best = neg_inf
        bidx = jnp.int32(0)
        for sub in range(2):
            for s in range(B):
                sh_s = sh_ref[s]  # [P, OB]
                dn = (((1,), (1,)), ((), ()))
                a = jax.lax.dot_general(e, sh_s, dn, preferred_element_type=jnp.float32)
                d = jax.lax.dot_general(ea, jnp.abs(sh_s), dn, preferred_element_type=jnp.float32)
                cnt = (d + a) * 0.5 if sub == 0 else (d - a) * 0.5
                score = cnt * weight - pen_dl
                valid = cnt >= 2.0
                if s == 0:
                    valid &= upper
                valid &= (absolute == 0.0) | (score >= 0.0)
                sc = jnp.where(valid, score, neg_inf)
                m = jnp.max(sc)
                loc = jnp.min(jnp.where(sc == m, iota2, big))
                flat = jnp.int32((sub * B + s) * P * P) + loc
                upd = m > best
                best = jnp.where(upd, m, best)
                bidx = jnp.where(upd, flat, bidx)

        out_ref[0, 0] = bidx
        out_ref[0, 1] = (best != neg_inf).astype(jnp.int32)

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, 2), jnp.int32),
        in_specs=[_vspec(), _vspec(), _vspec(), _vspec(), _sspec()],
        out_specs=_vspec(),
        interpret=interpret,
    )

    def select(e, sh, nov, dlat, coef):
        out = call(e, sh, nov, dlat, coef)
        return out[0, 0], out[0, 1] != 0

    return select
