"""Graph -> DAIS IR lowering: gather, topologically order, encode, DSE.

Each traced variable lowers to one Op; factors (free power-of-two scales and
negations) are folded into op data/opcode signs. Dead statement elimination
runs backward liveness and compacts indices.

Behavioral parity: reference src/da4ml/trace/tracer.py.
"""

from __future__ import annotations

from collections.abc import Sequence
from decimal import Decimal
from math import log2

import numpy as np

from ..ir.comb import CombLogic
from ..ir.types import Op, QInterval
from .fixed_variable import FixedVariable, const_f, table_context


def _recursive_gather(v: FixedVariable, gathered: dict[int, FixedVariable]):
    if v.id in gathered:
        return
    for p in v._from:
        _recursive_gather(p, gathered)
    gathered[v.id] = v


def gather_variables(inputs: Sequence[FixedVariable], outputs: Sequence[FixedVariable]):
    """Collect the transitive graph, stably sorted by (latency, insertion),
    with unreferenced non-input variables pruned."""
    input_ids = {v.id for v in inputs}
    gathered = {v.id: v for v in inputs}
    for o in outputs:
        _recursive_gather(o, gathered)
    variables = list(gathered.values())

    n = len(variables)
    order = sorted(range(n), key=lambda i: variables[i].latency * n + i)
    variables = [variables[i] for i in order]

    refcount = {v.id: 0 for v in variables}
    for v in variables:
        if v.id in input_ids:
            continue
        for p in v._from:
            refcount[p.id] += 1
    for v in outputs:
        refcount[v.id] += 1

    variables = [v for v in variables if refcount[v.id] > 0 or v.id in input_ids]
    index = {v.id: i for i, v in enumerate(variables)}
    return variables, index


def _comb_trace(inputs: Sequence[FixedVariable], outputs: Sequence[FixedVariable]):
    variables, index = gather_variables(inputs, outputs)
    ops: list[Op] = []
    inp_ids = {v.id: i for i, v in enumerate(inputs)}
    lookup_tables: list = []

    table_map: dict[int, int] = {}
    for v in variables:
        if v.opr != 'lookup':
            continue
        assert v._data is not None
        idx = int(v._data)
        if idx not in table_map:
            table_map[idx] = len(lookup_tables)
            lookup_tables.append(table_context.get_table_from_index(idx))

    for i, v in enumerate(variables):
        if v.id in inp_ids and v.opr != 'const':
            ops.append(Op(inp_ids[v.id], -1, -1, 0, v.unscaled.qint, v.latency, 0.0))
            continue
        if v.opr == 'new':
            raise NotImplementedError('Operation "new" is only expected in the input list')

        opr = v.opr
        if opr == 'vadd':
            v0, v1 = v._from
            f0, f1 = v0._factor, v1._factor
            id0, id1 = index[v0.id], index[v1.id]
            sub = int(f1 < 0)
            data = int(log2(abs(f1 / f0)))
            assert id0 < i and id1 < i, f'{id0} {id1} {i} {v.id}'
            op = Op(id0, id1, sub, data, v.unscaled.qint, v.latency, v.cost)
        elif opr == 'cadd':
            (v0,) = v._from
            id0 = index[v0.id]
            assert v._data is not None
            qint = v.unscaled.qint
            data = int(v._data / Decimal(qint.step))
            assert id0 < i
            op = Op(id0, -1, 4, data, qint, v.latency, v.cost)
        elif opr == 'wrap':
            (v0,) = v._from
            id0 = index[v0.id]
            assert id0 < i
            opcode = -3 if v0._factor < 0 else 3
            op = Op(id0, -1, opcode, 0, v.unscaled.qint, v.latency, v.cost)
        elif opr == 'relu':
            (v0,) = v._from
            id0 = index[v0.id]
            assert id0 < i
            opcode = -2 if v0._factor < 0 else 2
            op = Op(id0, -1, opcode, 0, v.unscaled.qint, v.latency, v.cost)
        elif opr == 'const':
            qint = v.unscaled.qint
            assert qint.min == qint.max, f'const {v.id} {qint.min} {qint.max}'
            f = const_f(qint.min)
            step = 2.0**-f
            qint = QInterval(qint.min, qint.min, step)
            op = Op(-1, -1, 5, int(qint.min / step), qint, v.latency, v.cost)
        elif opr == 'msb_mux':
            qint = v.unscaled.qint
            key, in0, in1 = v._from
            opcode = 6 if in1._factor > 0 else -6
            idk, id0, id1 = index[key.id], index[in0.id], index[in1.id]
            shift = int(log2(abs(in1._factor / in0._factor)))
            data = idk + (shift << 32)
            assert idk < i and id0 < i and id1 < i
            assert key._factor > 0, f'Cannot mux on v{key.id} with negative factor {key._factor}'
            op = Op(id0, id1, opcode, data, qint, v.latency, v.cost)
        elif opr == 'vmul':
            v0, v1 = v._from
            id0, id1 = index[v0.id], index[v1.id]
            assert id0 < i and id1 < i
            op = Op(id0, id1, 7, 0, v.unscaled.qint, v.latency, v.cost)
        elif opr == 'lookup':
            (v0,) = v._from
            id0 = index[v0.id]
            assert v._data is not None and id0 < i
            op = Op(id0, -1, 8, table_map[int(v._data)], v.unscaled.qint, v.latency, v.cost)
        elif opr == 'bit_unary':
            (v0,) = v._from
            id0 = index[v0.id]
            assert v._data is not None and id0 < i
            opcode = 9 if v._factor > 0 else -9
            op = Op(id0, -1, opcode, int(v._data), v.unscaled.qint, v.latency, v.cost)
        elif opr == 'bit_binary':
            v0, v1 = v._from
            id0, id1 = index[v0.id], index[v1.id]
            assert v._data is not None and id0 < i and id1 < i
            f0, f1 = v0._factor, v1._factor
            # data: {subopcode[63:56], pad, v1_neg[33], v0_neg[32], shift[31:0]}
            data = int(log2(abs(f1 / f0))) & 0xFFFFFFFF
            data += (int(v._data) << 56) + (int(f0 < 0) << 32) + (int(f1 < 0) << 33)
            op = Op(id0, id1, 10, data, v.unscaled.qint, v.latency, v.cost)
        else:
            raise NotImplementedError(f'Operation "{opr}" is not supported in tracing')
        ops.append(op)

    out_index = [index[v.id] for v in outputs]
    return ops, out_index, tuple(lookup_tables) if lookup_tables else None


def _index_remap(op: Op, idx_map: dict[int, int]) -> Op:
    if op.opcode == -1:
        return op
    id0 = idx_map[op.id0] if op.id0 >= 0 else op.id0
    id1 = idx_map[op.id1] if op.id1 >= 0 else op.id1
    if abs(op.opcode) == 6:
        id_c = idx_map[op.data & 0xFFFFFFFF]
        data = id_c + (((op.data >> 32) & 0xFFFFFFFF) << 32)
    else:
        data = op.data
    return Op(id0, id1, op.opcode, data, op.qint, op.latency, op.cost)


def dead_statement_elimination(comb: CombLogic, keep_dead_inputs: bool = False) -> CombLogic:
    """Backward liveness + index compaction (reference tracer.py:178-211)."""
    dead = np.ones(len(comb.ops), dtype=bool)
    for idx in comb.out_idxs:
        if idx != -1:
            dead[idx] = False

    for i in range(len(comb.ops) - 1, -1, -1):
        op = comb.ops[i]
        if dead[i] and not (keep_dead_inputs and op.opcode == -1):
            continue
        if op.id0 >= 0:
            dead[op.id0] = False
        if op.id1 >= 0:
            dead[op.id1] = False
        if abs(op.opcode) == 6:
            dead[op.data & 0xFFFFFFFF] = False

    new_idxs = np.cumsum(~dead) - 1
    idx_map = {i: int(new_idxs[i]) for i in range(len(comb.ops))}
    new_ops = [_index_remap(op, idx_map) for i, op in enumerate(comb.ops) if not dead[i]]
    new_out_idxs = [idx_map[idx] if idx >= 0 else -1 for idx in comb.out_idxs]
    return CombLogic(
        comb.shape,
        comb.inp_shifts,
        new_out_idxs,
        comb.out_shifts,
        comb.out_negs,
        new_ops,
        comb.carry_size,
        comb.adder_size,
        comb.lookup_tables,
    )


def comb_trace(inputs, outputs, keep_dead_inputs: bool = False) -> CombLogic:
    """Lower a traced computation (inputs -> outputs) to a CombLogic."""
    if isinstance(inputs, FixedVariable):
        inputs = [inputs]
    if isinstance(outputs, FixedVariable):
        outputs = [outputs]
    inputs, outputs = list(np.ravel(inputs)), list(np.ravel(outputs))

    assert all(inp._factor > 0 for inp in inputs), 'Input variables must have positive scaling factor'

    if any(not isinstance(v, FixedVariable) for v in outputs):
        hwconf = inputs[0].hwconf
        outputs = [v if isinstance(v, FixedVariable) else FixedVariable.from_const(v, hwconf, 1) for v in outputs]

    ops, out_index, lookup_tables = _comb_trace(inputs, outputs)
    shape = len(inputs), len(outputs)
    out_sf = [v._factor for v in outputs]
    comb = CombLogic(
        shape,
        [0] * shape[0],
        out_index,
        [int(log2(abs(sf))) for sf in out_sf],
        [sf < 0 for sf in out_sf],
        ops,
        outputs[0].hwconf.carry_size,
        outputs[0].hwconf.adder_size,
        lookup_tables,
    )
    return dead_statement_elimination(comb, keep_dead_inputs)
