from .conv_utils import avg_pool2d, conv1d, conv2d, max_pool2d
from .einsum_utils import einsum
from .quantization import fixed_quantize, quantize, relu
from .reduce_utils import reduce
from .sorting import sort

__all__ = [
    'einsum',
    'quantize',
    'relu',
    'reduce',
    'sort',
    'fixed_quantize',
    'conv1d',
    'conv2d',
    'max_pool2d',
    'avg_pool2d',
]
