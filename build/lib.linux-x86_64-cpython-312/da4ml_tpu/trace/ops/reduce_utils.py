"""Balanced reductions producing latency-optimal adder trees.

A reduction over symbolic fixed-point values is scheduled like a job queue:
every value gets a readiness rank, and the two lowest-ranked values are
combined first, with the merged value re-entering the queue at its own rank.
Ranking by (latency, factor sign, k+i bits) yields the same latency-optimal
trees as the reference's packet heap (behavioral parity with
src/da4ml/trace/ops/reduce_utils.py of calad0i/da4ml; implementation is
original — key function + tuple heap instead of a comparator class).

Combination order never changes the numeric result: fixed-point adds are
exact, so only cost/latency of the emitted tree depends on the schedule.
"""

from __future__ import annotations

import heapq
from collections.abc import Callable, Sequence
from functools import reduce as _fold
from math import prod

import numpy as np

from ..fixed_variable import FixedVariable

#: rank for non-symbolic operands: merge before any symbolic value
_EAGER_RANK = (-1.0, 0, 0)


def _merge_rank(v) -> tuple[float, int, int]:
    """Scheduling rank: earlier-ready, negative-factor, narrower merge first.

    Latency dominates so a freshly merged value (whose latency is the max of
    its operands plus the add delay) sinks behind still-unmerged cheap leaves;
    negative-factor values merge first so subtractions fold into the tree
    early (the reference packet order); the k+i width keeps accumulator
    growth balanced across the tree.
    """
    if not isinstance(v, FixedVariable):
        return _EAGER_RANK
    kif = v.kif
    return (v.latency, int(v._factor > 0), kif[0] + kif[1])


def _reduce(operator: Callable, items: Sequence):
    """Combine ``items`` pairwise, cheapest-rank first."""
    if isinstance(items, np.ndarray):
        items = list(items.ravel())
    if not items:
        raise ValueError('cannot reduce an empty sequence')
    if len(items) == 1:
        return items[0]
    if not isinstance(items[0], FixedVariable):
        return _fold(operator, items)

    # (rank, seq, value): seq makes ties deterministic (FIFO) and keeps the
    # heap from ever comparing two FixedVariables directly
    queue = [(_merge_rank(v), n, v) for n, v in enumerate(items)]
    heapq.heapify(queue)
    seq = len(items)
    while len(queue) > 1:
        a = heapq.heappop(queue)[2]
        b = heapq.heappop(queue)[2]
        merged = operator(a, b)
        heapq.heappush(queue, (_merge_rank(merged), seq, merged))
        seq += 1
    return queue[0][2]


def reduce(operator: Callable, x, axis=None, keepdims: bool = False):
    """Reduce over the given axes with balanced (heap) combination order."""
    from ..fixed_variable_array import FixedVariableArray

    wrapped = isinstance(x, FixedVariableArray)
    arr = x._vars if wrapped else x

    ndim = arr.ndim

    def _norm(a: int) -> int:
        if not -ndim <= a < ndim:
            raise np.exceptions.AxisError(a, ndim)
        return a % ndim

    if axis is None:
        red_axes = set(range(ndim))
    elif isinstance(axis, int):
        red_axes = {_norm(axis)}
    else:
        red_axes = {_norm(a) for a in axis}

    # move reduced axes to the back (stable among kept / among reduced),
    # then every row of the flattened view is one independent reduction
    order = [a for a in range(ndim) if a not in red_axes] + [a for a in range(ndim) if a in red_axes]
    n_red = prod(arr.shape[a] for a in red_axes)
    rows = np.transpose(arr, order).reshape(-1, n_red)
    out = np.array([_reduce(operator, row) for row in rows])

    if keepdims:
        shape = tuple(1 if a in red_axes else d for a, d in enumerate(arr.shape))
    else:
        shape = tuple(d for a, d in enumerate(arr.shape) if a not in red_axes)
    out = out.reshape(shape)

    if wrapped:
        res = FixedVariableArray(out, x.solver_options, hwconf=x.hwconf)
        return res._vars.item() if res.shape == () else res
    return out if out.shape != () or keepdims else out.item()
