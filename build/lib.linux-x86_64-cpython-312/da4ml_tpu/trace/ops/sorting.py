"""Hardware sorting networks: compare-swap cells built from MSB muxes.

The network is built as *data* first — a list of ``(i, j, up)`` comparator
cells — and then applied to the symbolic rows, so the wiring (Batcher
odd-even mergesort by default, bitonic optionally) is decoupled from the
cell implementation. Non-pow2 lengths are padded with out-of-range
sentinels; an optional payload (``aux_value``) rides along with each key
for argsort-style gathers.

Behavioral parity with src/da4ml/trace/ops/sorting.py of calad0i/da4ml
(same cell semantics and tie behavior); the network construction here is
the recursive odd-even-merge / bitonic formulations, emitted as comparator
lists rather than executed in place.
"""

from __future__ import annotations

from functools import lru_cache
from math import ceil, log2

import numpy as np

from ..fixed_variable import FixedVariable


@lru_cache(maxsize=None)
def _batcher_network(n: int) -> tuple[tuple[int, int, bool], ...]:
    """Comparator list for Batcher's odd-even mergesort of ``n`` (pow2) wires."""
    cells: list[tuple[int, int, bool]] = []

    def merge(lo: int, hi: int, stride: int) -> None:
        # merge the two sorted halves of wires lo..hi taken at ``stride``
        step = stride * 2
        if step < hi - lo:
            merge(lo, hi, step)
            merge(lo + stride, hi, step)
            for w in range(lo + stride, hi - stride, step):
                cells.append((w, w + stride, True))
        else:
            cells.append((lo, lo + stride, True))

    def build(lo: int, hi: int) -> None:
        if hi - lo >= 1:
            mid = lo + (hi - lo) // 2
            build(lo, mid)
            build(mid + 1, hi)
            merge(lo, hi, 1)

    build(0, n - 1)
    return tuple(cells)


@lru_cache(maxsize=None)
def _bitonic_network(n: int) -> tuple[tuple[int, int, bool], ...]:
    """Comparator list for a bitonic sort of ``n`` (pow2) wires."""
    cells: list[tuple[int, int, bool]] = []

    def merge(lo: int, span: int, up: bool) -> None:
        if span == 1:
            return
        half = span // 2
        for w in range(lo, lo + half):
            cells.append((w, w + half, up))
        merge(lo, half, up)
        merge(lo + half, half, up)

    def build(lo: int, span: int, up: bool) -> None:
        if span == 1:
            return
        half = span // 2
        build(lo, half, True)
        build(lo + half, half, False)
        merge(lo, span, up)

    build(0, n, True)
    return tuple(cells)


def _apply_cell(rows, i: int, j: int, up: bool) -> None:
    """One comparator: after this, key(rows[i]) <= key(rows[j]) iff ``up``.

    The swap condition is a single comparison of the keys (column 0); every
    column of both rows is then routed through an MSB mux pair on that
    condition, so payload columns travel with their key. Tie behavior matches
    the reference cell: equal keys hold position in an up cell and exchange
    in a down cell.
    """
    top, bot = rows[i], rows[j]
    swap = (top[0] > bot[0]) if up else (top[0] <= bot[0])
    n_col = len(top)
    new_top = np.empty(n_col, dtype=object)
    new_bot = np.empty(n_col, dtype=object)
    for c in range(n_col):
        new_top[c] = swap.msb_mux(bot[c], top[c], zt_sensitive=False)
        new_bot[c] = swap.msb_mux(top[c], bot[c], zt_sensitive=False)
    rows[i], rows[j] = new_top, new_bot


_NETWORKS = {'batcher': _batcher_network, 'bitonic': _bitonic_network}


def _pad_to_pow2(a):
    """Pad the sort axis to a power of two with below-min / above-max sentinels."""
    assert a.ndim == 3
    size = a.shape[-2]
    n_pad = 2 ** ceil(log2(size)) - size
    n_low, n_high = n_pad // 2, n_pad - n_pad // 2
    low, high, _ = a.lhs
    below = FixedVariable.from_const(float(np.min(low)) - 1, hwconf=a.hwconf)
    above = FixedVariable.from_const(float(np.max(high)) + 1, hwconf=a.hwconf)
    low_block = np.full((a.shape[0], n_low, a.shape[-1]), below)
    high_block = np.full((a.shape[0], n_high, a.shape[-1]), above)
    return np.concatenate([low_block, a, high_block], axis=-2), n_low, n_high


def sort(a, axis: int | None = None, kind: str = 'batcher', aux_value=None):
    from ..fixed_variable_array import FixedVariableArray  # noqa: F401  (type anchor)

    if isinstance(a, np.ndarray):
        return np.sort(a, axis=axis)
    if axis is None:
        axis = -1
    axis = axis % a.ndim

    if aux_value is not None:
        assert a.ndim == 1, f'aux_value requires 1D keys, got a.ndim={a.ndim}'
        assert a.shape[0] == aux_value.shape[0], f'length mismatch: {a.shape} vs {aux_value.shape}'
        if aux_value.shape == a.shape:
            aux_value = aux_value[..., None]
        assert aux_value.ndim - a.ndim == 1 and aux_value.shape[:-1] == a.shape
        a = np.concatenate([a[..., None], aux_value], axis=-1)
    else:
        a = a[..., None]

    sort_dim = a.shape[axis]
    r = np.moveaxis(a, axis, -2).copy()
    shape = r.shape
    r = r.reshape(-1, sort_dim, r.shape[-1])
    r, n_low, n_high = _pad_to_pow2(r)

    try:
        network = _NETWORKS[kind.lower()](r.shape[1])
    except KeyError:
        raise ValueError(f'Unsupported sorting algorithm: {kind}') from None
    for lane in range(len(r)):
        rows = list(r._vars[lane])
        for i, j, up in network:
            _apply_cell(rows, i, j, up)
        for i, row in enumerate(rows):
            r._vars[lane, i] = row

    r = r[:, n_low : r.shape[1] - n_high, :].reshape(shape)
    r = np.moveaxis(r, -2, axis)
    if aux_value is not None:
        return r[..., 0], r[..., 1:]
    assert r.shape[-1] == 1
    return r[..., 0]
