"""Pipeline stage splitting and retiming (compiler passes).

``to_pipeline`` splits a CombLogic at latency_cutoff boundaries, inserting
inter-stage register copies for values crossing stages. ``retime_pipeline``
binary-searches the smallest cutoff that preserves the stage count by
re-executing the IR symbolically with a new HWConfig — the latency-snap rule
in FixedVariable.get_cost_and_latency redistributes ops between stages.

Behavioral parity: reference src/da4ml/trace/pipeline.py.
"""

from __future__ import annotations

from math import floor

from ..ir.comb import CombLogic, Pipeline
from ..ir.types import Op
from .fixed_variable import FixedVariable, HWConfig
from .tracer import comb_trace


def retime_pipeline(pipe: Pipeline, verbose: bool = False) -> Pipeline:
    n_stages = len(pipe.stages)
    cutoff_high = max(max(sol.out_latency) / (i + 1) for i, sol in enumerate(pipe.stages))
    cutoff_low = max(pipe.out_latencies) / n_stages
    adder_size, carry_size = pipe.stages[0].adder_size, pipe.stages[0].carry_size
    best = pipe
    while cutoff_high - cutoff_low > 1:
        cutoff = (cutoff_high + cutoff_low) // 2
        hwconf = HWConfig(adder_size, carry_size, cutoff)
        inp = [FixedVariable(*qint, hwconf=hwconf) for qint in pipe.inp_qint]
        try:
            out = list(pipe(inp))
        except AssertionError:
            cutoff_low = cutoff
            continue
        cand = to_pipeline(comb_trace(inp, out), cutoff, retiming=False)
        if len(cand.stages) > n_stages:
            cutoff_low = cutoff
        else:
            cutoff_high = cutoff
            best = cand
    if verbose:
        print(f'actual cutoff: {cutoff_high}')
    return best


def _get_new_idx(
    idx: int,
    locator: list[dict[int, int]],
    opd: dict[int, list[Op]],
    out_idxd: dict[int, list[int]],
    ops: list[Op],
    stage: int,
    latency_cutoff: float,
) -> int:
    """Index of value `idx` within `stage`, materializing cross-stage register
    copies (input-copy ops) for every boundary crossed."""
    if idx < 0:
        return idx
    stages_present = locator[idx].keys()
    if stage not in stages_present:
        p0_stage = max(stages_present)
        p0_idx = locator[idx][p0_stage]
        for j in range(p0_stage, stage):
            op0 = ops[idx]
            latency = float(latency_cutoff * (j + 1))
            out_idxd.setdefault(j, []).append(locator[idx][j])
            copy_op = Op(len(out_idxd[j]) - 1, -1, -1, 0, op0.qint, latency, 0.0)
            opd.setdefault(j + 1, []).append(copy_op)
            p0_idx = len(opd[j + 1]) - 1
            locator[idx][j + 1] = p0_idx
    else:
        p0_idx = locator[idx][stage]
    return p0_idx


def to_pipeline(comb: CombLogic, latency_cutoff: float, retiming: bool = True, verbose: bool = False) -> Pipeline:
    """Split a CombLogic into an II=1 pipeline at the given latency cutoff."""
    assert len(comb.ops) > 0, 'No operations in the record'

    def get_stage(op: Op) -> int:
        return floor(op.latency / (latency_cutoff + 1e-9)) if latency_cutoff > 0 else 0

    opd: dict[int, list[Op]] = {}
    out_idxd: dict[int, list[int]] = {}
    locator: list[dict[int, int]] = []

    ops = list(comb.ops)
    lat = max(ops[i].latency for i in comb.out_idxs)
    for i in comb.out_idxs:
        # sentinel "emit to external output" markers
        ops.append(Op(i, -1001, -1001, 0, ops[i].qint, lat, 0.0))

    for op in ops:
        stage = get_stage(op)
        if op.opcode == -1:
            opd.setdefault(stage, []).append(op)
            locator.append({stage: len(opd[stage]) - 1})
            continue

        p0 = _get_new_idx(op.id0, locator, opd, out_idxd, ops, stage, latency_cutoff)
        p1 = _get_new_idx(op.id1, locator, opd, out_idxd, ops, stage, latency_cutoff)
        if op.opcode in (6, -6):
            k = _get_new_idx(op.data & 0xFFFFFFFF, locator, opd, out_idxd, ops, stage, latency_cutoff)
            data = ((op.data >> 32) & 0xFFFFFFFF) << 32 | k
        else:
            data = op.data

        if p1 == -1001:
            out_idxd.setdefault(stage, []).append(p0)
        else:
            opd.setdefault(stage, []).append(Op(p0, p1, op.opcode, data, op.qint, op.latency, op.cost))
            locator.append({stage: len(opd[stage]) - 1})

    stages = []
    max_stage = max(opd.keys())
    n_in = comb.shape[0]
    for stage in range(len(opd.keys())):
        _ops = opd[stage]
        _out_idx = out_idxd[stage]
        if stage == max_stage:
            out_shifts, out_negs = comb.out_shifts, comb.out_negs
        else:
            out_shifts, out_negs = [0] * len(_out_idx), [False] * len(_out_idx)

        if comb.lookup_tables is not None:
            _ops, lookup_tables = remap_table_idxs(comb, _ops)
        else:
            lookup_tables = None
        stages.append(
            CombLogic(
                shape=(n_in, len(_out_idx)),
                inp_shifts=[0] * n_in,
                out_idxs=_out_idx,
                out_shifts=out_shifts,
                out_negs=out_negs,
                ops=_ops,
                carry_size=comb.carry_size,
                adder_size=comb.adder_size,
                lookup_tables=lookup_tables,
            )
        )
        n_in = len(_out_idx)

    pipe = Pipeline(tuple(stages))
    if retiming:
        pipe = retime_pipeline(pipe, verbose=verbose)
    return pipe


def remap_table_idxs(comb: CombLogic, _ops: list[Op]):
    """Compact per-stage lookup table indices to the tables actually used."""
    assert comb.lookup_tables is not None
    table_idxs = sorted({op.data for op in _ops if op.opcode == 8})
    remap = {j: i for i, j in enumerate(table_idxs)}
    out_ops = [
        Op(op.id0, op.id1, op.opcode, remap[op.data], op.qint, op.latency, op.cost) if op.opcode == 8 else op for op in _ops
    ]
    return out_ops, tuple(comb.lookup_tables[i] for i in table_idxs)
