from .fixed_variable import FixedVariable, FixedVariableInput, HWConfig
from .fixed_variable_array import FixedVariableArray, FixedVariableArrayInput, LazyUnaryArray
from .pipeline import retime_pipeline, to_pipeline
from .tracer import comb_trace

__all__ = [
    'FixedVariable',
    'FixedVariableInput',
    'HWConfig',
    'FixedVariableArray',
    'FixedVariableArrayInput',
    'LazyUnaryArray',
    'comb_trace',
    'to_pipeline',
    'retime_pipeline',
]
