from .numeric import apply_binary_bit_op, apply_quantize, apply_relu, apply_unary_bit_op

__all__ = ['apply_quantize', 'apply_relu', 'apply_unary_bit_op', 'apply_binary_bit_op']
