"""Lookup tables shared by the IR, tracer, interpreters and codegen.

Tables are deduplicated globally by content hash. A table stores integer
entries at a fixed output quantization (``out_qint``); numeric lookup maps the
input value to a table index via the input's QInterval.

Behavioral parity: reference src/da4ml/trace/fixed_variable.py:33-198
(TraceContext/TableSpec/LookupTable).
"""

from __future__ import annotations

from dataclasses import dataclass
from hashlib import sha256
from math import ceil, floor, log2

import numpy as np
from numpy.typing import NDArray

from .types import Precision, QInterval, minimal_kif


def lsb_loc(x: float) -> int:
    """Location of the least-significant set bit of a float (power-of-2 exponent).

    Returns 127 for zero (sentinel). Parity: reference bit_decompose.cc:10-20,
    implemented via the float's exact binary fraction rather than bit twiddling.
    """
    if x == 0.0:
        return 127
    x = abs(float(np.float32(x)))
    e = 0
    # scale mantissa to an odd integer; exponent of the lowest set bit
    m, ex = np.frexp(np.float64(x))
    # m in [0.5, 1); x = m * 2**ex. Lowest set bit of m*2**24 gives lsb.
    mi = int(m * (1 << 24))
    tz = (mi & -mi).bit_length() - 1
    return int(ex - 24 + tz)


def interpret_as(x, k: int | bool, i: int, f: int):
    """Reinterpret integer value(s) ``x`` as fixed-point (k, i, f) with wrap.

    Parity: reference fixed_variable.py:100-110.
    """
    b = int(k) + i + f
    bias = 2.0 ** (b - 1) * int(k)
    eps = 2.0**-f
    floor_fn = np.floor if isinstance(x, np.ndarray) else floor
    return eps * (floor_fn(x + bias) % 2.0**b - bias)


@dataclass
class TableSpec:
    hash: str
    out_qint: QInterval
    inp_width: int

    @property
    def out_kif(self) -> Precision:
        return minimal_kif(self.out_qint)


def table_spec(values: NDArray[np.floating]) -> tuple[TableSpec, NDArray[np.int32]]:
    """Quantize a float table to integers at its minimal fractional precision."""
    f_out = max(-lsb_loc(float(v)) for v in values.ravel())
    int_table = np.asarray(np.round(values * 2.0**f_out), dtype=np.int32)
    h = sha256(int_table.tobytes())
    h.update(f'{f_out}'.encode())
    out_qint = QInterval(float(np.min(values)), float(np.max(values)), float(2.0**-f_out))
    return TableSpec(hash=h.hexdigest(), out_qint=out_qint, inp_width=ceil(log2(values.size))), int_table


class LookupTable:
    """An integer-valued lookup table with fixed output quantization."""

    def __init__(self, values: NDArray, spec: TableSpec | None = None):
        assert values.ndim == 1, 'Lookup table values must be 1-dimensional'
        if spec is not None:
            assert values.dtype == np.int32
            self.spec, self.table = spec, values
        else:
            self.spec, self.table = table_spec(np.asarray(values, dtype=np.float64))

    def lookup(self, value, qint_in: QInterval | tuple[float, float, float]):
        """Numeric lookup: map a float value to its table entry (as float).

        Symbolic values (anything exposing ``.lookup``) are routed back to the
        tracer so the op lands in the graph.
        """
        if hasattr(value, 'lookup') and not isinstance(value, (float, int, np.floating, np.integer)):
            return value.lookup(self, original_qint=qint_in)
        lo, hi, step = qint_in
        assert lo <= value <= hi, f'Value {value} out of range [{lo}, {hi}]'
        index = round((value - lo) / step)
        k, i, f = self.spec.out_kif
        return interpret_as(int(self.table[index]), k, i, f)

    @property
    def float_table(self) -> NDArray[np.floating]:
        k, i, f = self.spec.out_kif
        return interpret_as(self.table, k, i, f)

    def pads(self, key_qint: QInterval) -> tuple[int, int]:
        """Left/right padding aligning the table to the key's binary index space.

        Parity: reference fixed_variable.py:169-177 (``_get_pads``).
        """
        k, i, f = minimal_kif(key_qint)
        if k:
            pad_left = round((key_qint.min + 2**i) / key_qint.step)
        else:
            pad_left = round(key_qint.min / key_qint.step)
        size = 2 ** (int(k) + i + f)
        return pad_left, size - len(self.table) - pad_left

    def padded_table(self, key_qint: QInterval) -> NDArray[np.float64]:
        """Table indexed directly by the key's raw binary representation.

        Unreachable entries are NaN; for signed keys the array is rolled so
        negative two's-complement codes index the upper half.
        """
        pad_left, pad_right = self.pads(key_qint)
        data = np.pad(self.table.astype(np.float64), (pad_left, pad_right), constant_values=np.nan)
        if key_qint.min < 0:
            data = np.roll(data, len(data) // 2)
        return data

    def to_dict(self) -> dict:
        return {
            'spec': {
                'hash': self.spec.hash,
                'out_qint': list(self.spec.out_qint),
                'inp_width': self.spec.inp_width,
            },
            'table': self.table.tolist(),
        }

    @classmethod
    def from_dict(cls, data: dict) -> 'LookupTable':
        sd = data['spec']
        qint = sd['out_qint']
        if isinstance(qint, dict):  # tolerate reference-style dict encoding
            qint = [qint['min'], qint['max'], qint['step']]
        spec = TableSpec(hash=sd['hash'], out_qint=QInterval(*qint), inp_width=sd['inp_width'])
        return cls(np.array(data['table'], dtype=np.int32), spec=spec)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, LookupTable) and self.spec == other.spec and np.array_equal(self.table, other.table)
        )

    def __len__(self) -> int:
        return len(self.table)
