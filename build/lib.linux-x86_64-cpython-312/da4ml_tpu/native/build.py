"""Build the native shared library with g++ (no meson/pybind11 dependency).

Usage: ``python -m da4ml_tpu.native.build [--force]``. The library is also
auto-built on first use (bindings.load_lib) unless DA4ML_NO_NATIVE_BUILD is
set. Output: ``_da4ml_native.so`` next to this file.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

_HERE = Path(__file__).parent
SRC_DIR = _HERE / 'src'
LIB_PATH = _HERE / '_da4ml_native.so'


def _sources() -> list[Path]:
    return sorted(SRC_DIR.glob('*.cc'))


def needs_build() -> bool:
    if not LIB_PATH.exists():
        return True
    lib_mtime = LIB_PATH.stat().st_mtime
    deps = list(SRC_DIR.glob('*.cc')) + list(SRC_DIR.glob('*.hh'))
    return any(p.stat().st_mtime > lib_mtime for p in deps)


def build(force: bool = False, verbose: bool = False) -> Path:
    if not force and not needs_build():
        return LIB_PATH
    cxx = os.environ.get('CXX', 'g++')
    cmd = [
        cxx,
        '-std=c++20',
        '-O3',
        '-fPIC',
        '-shared',
        '-fopenmp',
        '-fvisibility=hidden',
        '-Wall',
        *[str(s) for s in _sources()],
        '-o',
        str(LIB_PATH),
    ]
    if verbose:
        print(' '.join(cmd), file=sys.stderr)
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f'native build failed:\n{proc.stderr}')
    return LIB_PATH


if __name__ == '__main__':
    force = '--force' in sys.argv
    path = build(force=force, verbose=True)
    print(f'built {path}')
