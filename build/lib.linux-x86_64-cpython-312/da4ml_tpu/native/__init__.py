"""Native (C++) runtime components: DAIS interpreter and CMVM solver.

The shared library is built on demand from da4ml_tpu/native/src via
``python -m da4ml_tpu.native.build``; bindings go through ctypes (no
pybind11 dependency). Until built, ``is_available()`` is False and entry
points raise a clear error.
"""

from __future__ import annotations


def is_available() -> bool:
    try:
        from .bindings import load_lib

        return load_lib() is not None
    except Exception:
        return False


def has_solver() -> bool:
    """True when the native CMVM solver (cmvm_solve symbol) is built."""
    try:
        from .bindings import load_lib

        lib = load_lib()
        return lib is not None and hasattr(lib, 'cmvm_solve')
    except Exception:
        return False


def run_binary(binary, data, n_threads: int = 0):
    from .bindings import run_binary as _run

    return _run(binary, data, n_threads=n_threads)


def solve_native(kernel, **kwargs):
    from .bindings import solve_native as _solve

    return _solve(kernel, **kwargs)
