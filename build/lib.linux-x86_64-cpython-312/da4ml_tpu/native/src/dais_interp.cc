// Native DAIS batch runner: OpenMP over sample chunks, one exec buffer per
// thread. C-ABI entry points consumed via ctypes (da4ml_tpu/native/bindings.py).
//
// Parity targets (reference, /root/reference): src/da4ml/_binary/dais/
// bindings.cc:30-100 (chunked omp batch, exception funnel) and
// DAISInterpreter.cc (op semantics — see dais_common.hh).

#include <algorithm>
#include <atomic>
#include <cstring>

#include <omp.h>

#include "dais_common.hh"

namespace {

void copy_error(const std::string& msg, char* err, int64_t err_len) {
    if (!err || err_len <= 0) return;
    int64_t n = std::min<int64_t>(int64_t(msg.size()), err_len - 1);
    std::memcpy(err, msg.data(), size_t(n));
    err[n] = '\0';
}

}  // namespace

#define DA4ML_API extern "C" __attribute__((visibility("default")))

// Run a DAIS program over a (n_samples, n_in) float64 batch.
// Returns 0 on success, nonzero with a message in `err` otherwise.
DA4ML_API int dais_run(const int32_t* binary, int64_t binary_len, const double* data, int64_t n_samples, double* out,
             int64_t n_threads, char* err, int64_t err_len) {
    try {
        da4ml::DaisProgram prog = da4ml::DaisProgram::from_binary(binary, binary_len);
        const int64_t n_in = prog.n_in, n_out = prog.n_out;

        int threads = n_threads > 0 ? int(n_threads) : omp_get_max_threads();
        // At least 32 samples per chunk so tiny batches don't pay thread
        // overhead (reference dais/bindings.cc:58-64).
        const int64_t chunk = std::max<int64_t>(32, (n_samples + threads - 1) / std::max(threads, 1));
        const int64_t n_chunks = (n_samples + chunk - 1) / chunk;

        std::atomic<bool> failed{false};
        std::string first_error;

#pragma omp parallel for schedule(static) num_threads(threads)
        for (int64_t c = 0; c < n_chunks; ++c) {
            if (failed.load(std::memory_order_relaxed)) continue;
            std::vector<int64_t> buf(size_t(prog.n_ops));
            const int64_t lo = c * chunk, hi = std::min(n_samples, lo + chunk);
            try {
                for (int64_t s = lo; s < hi; ++s)
                    da4ml::exec_sample(prog, data + s * n_in, buf.data(), out + s * n_out);
            } catch (const std::exception& e) {
                bool expected = false;
                if (failed.compare_exchange_strong(expected, true)) {
#pragma omp critical(dais_err)
                    first_error = e.what();
                }
            }
        }
        if (failed.load()) {
            copy_error(first_error, err, err_len);
            return 2;
        }
        return 0;
    } catch (const std::exception& e) {
        copy_error(e.what(), err, err_len);
        return 1;
    }
}

// Introspection helper: op count / max width of a serialized program.
DA4ML_API int dais_program_info(const int32_t* binary, int64_t binary_len, int64_t* n_in, int64_t* n_out, int64_t* n_ops,
                      int64_t* max_width, char* err, int64_t err_len) {
    try {
        da4ml::DaisProgram prog = da4ml::DaisProgram::from_binary(binary, binary_len);
        *n_in = prog.n_in;
        *n_out = prog.n_out;
        *n_ops = prog.n_ops;
        int w = 0;
        for (int i = 0; i < prog.n_ops; ++i) w = std::max(w, int(prog.width(i)));
        *max_width = w;
        return 0;
    } catch (const std::exception& e) {
        copy_error(e.what(), err, err_len);
        return 1;
    }
}

DA4ML_API int da4ml_native_abi_version() { return 1; }
