// Native CMVM solver: CSD decomposition, Prim-MST kernel split, greedy CSE
// with mc/wmc(-dc/-pdc) heuristics, balanced heap adder-tree emission, and an
// OpenMP sweep over decomposition depths.
//
// Decision-identical with the Python host solver (da4ml_tpu/cmvm/*.py): the
// frequency map iterates in sorted Pair order (id1, id0, sub, shift) with
// >=-argmax and the reduction heap is keyed on the same total order, so both
// implementations produce the same op list. Parity targets in the reference
// tree: src/da4ml/_binary/cmvm/{bit_decompose,mat_decompose,state_opr,
// indexers,cmvm_core,api}.cc.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <map>
#include <queue>
#include <stdexcept>
#include <string>
#include <tuple>
#include <vector>

#include <omp.h>

namespace da4ml_cmvm {

constexpr double INF = std::numeric_limits<double>::infinity();

struct QInt {
    double min = 0, max = 0, step = 1;
};

struct OpC {
    int32_t id0, id1, opcode;
    int64_t data;
    QInt qint;
    double latency, cost;
};

struct CombC {
    int32_t n_in = 0, n_out = 0;
    std::vector<int32_t> inp_shifts, out_idxs, out_shifts, out_negs;
    std::vector<OpC> ops;
    int32_t carry_size = -1, adder_size = -1;

    double cost() const {
        double c = 0;
        for (const auto& op : ops) c += op.cost;
        return c;
    }
    std::vector<QInt> out_qint() const {
        std::vector<QInt> out(n_out);
        for (int i = 0; i < n_out; ++i) {
            int idx = out_idxs[i];
            if (idx < 0) {
                out[i] = QInt{0, 0, 1};
                continue;
            }
            const QInt& q = ops[idx].qint;
            double sf = std::ldexp(1.0, out_shifts[i]);
            double lo = q.min * sf, hi = q.max * sf, st = q.step * sf;
            if (out_negs[i]) out[i] = QInt{-hi, -lo, st};
            else out[i] = QInt{lo, hi, st};
        }
        return out;
    }
    std::vector<double> out_latency() const {
        std::vector<double> out(n_out);
        for (int i = 0; i < n_out; ++i) out[i] = out_idxs[i] >= 0 ? ops[out_idxs[i]].latency : 0.0;
        return out;
    }
    double max_out_latency() const {
        double m = 0;
        for (int i = 0; i < n_out; ++i) m = std::max(m, out_idxs[i] >= 0 ? ops[out_idxs[i]].latency : 0.0);
        return m;
    }
};

struct PipeC {
    CombC stages[2];
    double cost() const { return stages[0].cost() + stages[1].cost(); }
};

// ------------------------------------------------------------------ CSD

// Exponent of the lowest set bit of a float32-rounded value; 127 for zero.
// (da4ml_tpu/ir/lut.py lsb_loc; reference bit_decompose.cc:10-20)
inline int lsb_loc(double x) {
    if (x == 0.0) return 127;
    double xf = std::fabs(double(float(x)));
    int ex;
    double m = std::frexp(xf, &ex);
    int64_t mi = int64_t(m * double(int64_t(1) << 24));
    int tz = __builtin_ctzll(uint64_t(mi));
    return ex - 24 + tz;
}

// CSD digits (-1/0/1) of an integer array; threshold 2/3*2^n per bit plane.
// csd[idx][b] reconstructs as sum(digit * 2^b).
struct Csd {
    std::vector<int8_t> digits;  // flattened [size, n_bits]
    int n_bits = 0;
    int8_t at(size_t idx, int b) const { return digits[idx * n_bits + b]; }
};

inline Csd int_arr_to_csd(const std::vector<int64_t>& x) {
    int64_t max_val = 0;
    for (int64_t v : x) max_val = std::max<int64_t>(max_val, std::llabs(v));
    int n = std::max(int(std::ceil(std::log2(double(std::max<int64_t>(max_val, 1)) * 1.5))), 1);
    Csd out;
    out.n_bits = n;
    out.digits.assign(x.size() * n, 0);
    std::vector<int64_t> rem = x;
    for (int b = n - 1; b >= 0; --b) {
        int64_t p = int64_t(1) << b;
        int64_t thres = p * 2 / 3;
        for (size_t i = 0; i < rem.size(); ++i) {
            int8_t digit = rem[i] > thres ? 1 : (rem[i] < -thres ? -1 : 0);
            out.digits[i * n + b] = digit;
            rem[i] -= p * digit;
        }
    }
    return out;
}

// Factor per-column then per-row power-of-2 shifts so entries are odd ints.
inline void center(std::vector<double>& a, int n_in, int n_out, std::vector<int>& shift0, std::vector<int>& shift1) {
    shift1.assign(n_out, 127);
    for (int j = 0; j < n_out; ++j)
        for (int i = 0; i < n_in; ++i) shift1[j] = std::min(shift1[j], lsb_loc(a[i * n_out + j]));
    for (int j = 0; j < n_out; ++j)
        for (int i = 0; i < n_in; ++i) a[i * n_out + j] = std::ldexp(a[i * n_out + j], -shift1[j]);
    shift0.assign(n_in, 127);
    for (int i = 0; i < n_in; ++i)
        for (int j = 0; j < n_out; ++j) shift0[i] = std::min(shift0[i], lsb_loc(a[i * n_out + j]));
    for (int i = 0; i < n_in; ++i)
        for (int j = 0; j < n_out; ++j) a[i * n_out + j] = std::ldexp(a[i * n_out + j], -shift0[i]);
}

// ----------------------------------------------------------------- cost model

inline QInt qint_add(const QInt& q0, const QInt& q1, int shift, bool sub0, bool sub1) {
    double min0 = sub0 ? -q0.max : q0.min, max0 = sub0 ? -q0.min : q0.max;
    double min1 = sub1 ? -q1.max : q1.min, max1 = sub1 ? -q1.min : q1.max;
    double s = std::ldexp(1.0, shift);
    return QInt{min0 + min1 * s, max0 + max1 * s, std::min(q0.step, q1.step * s)};
}

// (latency_delta, cost) of one adder (da4ml_tpu/cmvm/cost.py cost_add).
inline std::pair<double, double> cost_add(const QInt& q0, const QInt& q1, int shift, bool sub, int adder_size,
                                          int carry_size) {
    if (adder_size < 0 && carry_size < 0) return {1.0, 1.0};
    double as = adder_size < 0 ? 65535 : adder_size;
    double cs = carry_size < 0 ? 65535 : carry_size;
    double min0 = q0.min, max0 = q0.max, step0 = q0.step;
    double min1 = q1.min, max1 = q1.max, step1 = q1.step;
    if (sub) std::swap(min1, max1);
    double sf = std::ldexp(1.0, shift);
    min1 *= sf;
    max1 *= sf;
    step1 *= sf;
    max0 += step0;
    max1 += step1;
    double f = -std::log2(std::max(step0, step1));
    double i = std::ceil(std::log2(std::max({std::fabs(min0), std::fabs(min1), std::fabs(max0), std::fabs(max1)})));
    double k = (q0.min < 0 || q1.min < 0) ? 1 : 0;
    double n_accum = k + i + f;
    return {std::ceil(n_accum / cs), std::ceil(n_accum / as)};
}

inline int iceil_log2(double x) { return x > 0 ? int(std::ceil(std::log2(x))) : 0; }

// (n_overlap, n_accum) bit counts for the wmc score.
inline std::pair<int, int> overlap_and_accum(const QInt& q0, const QInt& q1) {
    double min0 = q0.min, max0 = q0.max + q0.step;
    double min1 = q1.min, max1 = q1.max + q1.step;
    int f = -iceil_log2(std::max(q0.step, q1.step));
    int i_high = iceil_log2(std::max({std::fabs(min0), std::fabs(min1), std::fabs(max0), std::fabs(max1)}));
    int i_low = iceil_log2(std::min(std::max(std::fabs(min0), std::fabs(max0)), std::max(std::fabs(min1), std::fabs(max1))));
    int k = (q0.min < 0 || q1.min < 0) ? 1 : 0;
    return {k + i_low + f, k + i_high + f};
}

// --------------------------------------------------------------- CSE state

struct PairC {
    int32_t id0, id1;
    bool sub;
    int32_t shift;
    bool operator==(const PairC& o) const { return id0 == o.id0 && id1 == o.id1 && sub == o.sub && shift == o.shift; }
};

// Sort order (id1, id0, sub, shift) — the reference's flat-vector Pair order.
struct PairLess {
    bool operator()(const PairC& a, const PairC& b) const {
        return std::tie(a.id1, a.id0, a.sub, a.shift) < std::tie(b.id1, b.id0, b.sub, b.shift);
    }
};

inline int to_shift(int v) { return std::abs(v) - 1; }
inline int to_sign(int v) { return v > 0 ? 1 : -1; }
inline int encode_digit(int shift, int sign) { return sign * (shift + 1); }

inline PairC make_pair_c(int id0, int id1, int v0, int v1) {
    bool sub = to_sign(v0) != to_sign(v1);
    return PairC{id0, id1, sub, to_shift(v1) - to_shift(v0)};
}

using FreqMap = std::map<PairC, int, PairLess>;

struct DAStateC {
    std::vector<int> shift0, shift1;
    std::vector<std::vector<std::vector<int>>> expr;  // expr[i_in][i_out] -> encoded digits
    int n_bits = 0;
    std::vector<OpC> ops;
    FreqMap freq_stat;
    int n_in = 0, n_out = 0;
};

inline void count_pairs_into(FreqMap& stat, const std::vector<PairC>& raw) {
    FreqMap counts;
    for (const auto& p : raw) counts[p] += 1;
    for (const auto& [p, c] : counts)
        if (c >= 2) stat[p] = c;
}

inline void row_pairs(std::vector<PairC>& raw, int lo, int hi, const std::vector<int>& row_lo,
                      const std::vector<int>& row_hi) {
    if (row_lo.empty() || row_hi.empty()) return;
    if (lo == hi) {
        for (size_t a = 1; a < row_lo.size(); ++a)
            for (size_t b = 0; b < a; ++b) raw.push_back(make_pair_c(lo, lo, row_lo[a], row_lo[b]));
    } else {
        for (int v0 : row_lo)
            for (int v1 : row_hi) raw.push_back(make_pair_c(lo, hi, v0, v1));
    }
}

inline DAStateC create_state(const std::vector<double>& kernel, int n_in, int n_out, const std::vector<QInt>& qintervals,
                             const std::vector<double>& inp_latencies, bool no_stat_init) {
    DAStateC st;
    st.n_in = n_in;
    st.n_out = n_out;
    std::vector<double> centered = kernel;
    center(centered, n_in, n_out, st.shift0, st.shift1);
    std::vector<int64_t> ints(centered.size());
    for (size_t i = 0; i < centered.size(); ++i) ints[i] = int64_t(std::llround(centered[i]));
    for (int i = 0; i < n_in; ++i)
        if (qintervals[i].min == 0.0 && qintervals[i].max == 0.0)
            for (int j = 0; j < n_out; ++j) ints[i * n_out + j] = 0;
    Csd csd = int_arr_to_csd(ints);
    st.n_bits = csd.n_bits;

    st.expr.resize(n_in);
    for (int i = 0; i < n_in; ++i) {
        st.expr[i].resize(n_out);
        for (int io = 0; io < n_out; ++io) {
            auto& digits = st.expr[i][io];
            for (int b = 0; b < csd.n_bits; ++b) {
                int8_t v = csd.at(size_t(i) * n_out + io, b);
                if (v != 0) digits.push_back(encode_digit(b, v));
            }
        }
    }

    if (!no_stat_init) {
        std::vector<PairC> raw;
        for (int i_out = 0; i_out < n_out; ++i_out)
            for (int i0 = 0; i0 < n_in; ++i0)
                for (int i1 = i0; i1 < n_in; ++i1) row_pairs(raw, i0, i1, st.expr[i0][i_out], st.expr[i1][i_out]);
        count_pairs_into(st.freq_stat, raw);
    }

    for (int i = 0; i < n_in; ++i) {
        double sf = std::ldexp(1.0, st.shift0[i]);
        const QInt& q = qintervals[i];
        st.ops.push_back(OpC{i, -1, -1, 0, QInt{q.min * sf, q.max * sf, q.step * sf}, inp_latencies[i], 0.0});
    }
    return st;
}

inline OpC pair_to_op(const PairC& pair, const DAStateC& st, int adder_size, int carry_size) {
    auto [dlat, cost] = cost_add(st.ops[pair.id0].qint, st.ops[pair.id1].qint, pair.shift, pair.sub, adder_size, carry_size);
    double lat = std::max(st.ops[pair.id0].latency, st.ops[pair.id1].latency) + dlat;
    QInt qint = qint_add(st.ops[pair.id0].qint, st.ops[pair.id1].qint, pair.shift, false, pair.sub);
    return OpC{pair.id0, pair.id1, pair.sub ? 1 : 0, pair.shift, qint, lat, cost};
}

inline void update_expr(DAStateC& st, const PairC& pair, int adder_size, int carry_size) {
    st.ops.push_back(pair_to_op(pair, st, adder_size, carry_size));

    int id0 = pair.id0, id1 = pair.id1, rel_shift = pair.shift;
    bool flip = false;
    if (rel_shift < 0) {
        std::swap(id0, id1);
        rel_shift = -rel_shift;
        flip = true;
    }
    int target_sign = pair.sub ? -1 : 1;

    std::vector<std::vector<int>> new_slice(st.n_out);
    for (int i_out = 0; i_out < st.n_out; ++i_out) {
        auto& row0 = st.expr[id0][i_out];
        auto& row1 = st.expr[id1][i_out];  // aliases row0 when id0 == id1
        for (size_t loc0 = 0; loc0 < row0.size(); ++loc0) {
            int v0 = row0[loc0];
            if (v0 == 0) continue;
            int s0 = to_shift(v0), g0 = to_sign(v0);
            int s1 = s0 + rel_shift;
            if (s1 >= st.n_bits) continue;
            int loc1 = -1;
            for (size_t j = 0; j < row1.size(); ++j)
                if (to_shift(row1[j]) == s1) {
                    loc1 = int(j);
                    break;
                }
            int g1 = loc1 >= 0 ? to_sign(row1[loc1]) : 0;
            if (target_sign * g1 * g0 != 1) continue;
            new_slice[i_out].push_back(flip ? encode_digit(s1, g1) : encode_digit(s0, g0));
            row0[loc0] = 0;
            row1[loc1] = 0;
        }
        auto compact = [](std::vector<int>& row) { row.erase(std::remove(row.begin(), row.end(), 0), row.end()); };
        compact(st.expr[id0][i_out]);
        if (id0 != id1) compact(st.expr[id1][i_out]);
    }
    st.expr.push_back(std::move(new_slice));
}

inline void update_stats(DAStateC& st, const PairC& pair) {
    int id0 = pair.id0, id1 = pair.id1;
    for (auto it = st.freq_stat.begin(); it != st.freq_stat.end();) {
        const PairC& p = it->first;
        if (p.id0 == id0 || p.id0 == id1 || p.id1 == id0 || p.id1 == id1)
            it = st.freq_stat.erase(it);
        else
            ++it;
    }
    int n_constructed = int(st.expr.size());
    std::vector<int> modified = {n_constructed - 1, id0};
    if (id0 != id1) modified.push_back(id1);

    std::vector<PairC> raw;
    for (int i_out = 0; i_out < st.n_out; ++i_out)
        for (int in1 = 0; in1 < n_constructed; ++in1)
            for (int in0 : modified) {
                if ((in1 == n_constructed - 1 || in1 == id0 || in1 == id1) && in0 > in1) continue;
                int lo = std::min(in0, in1), hi = std::max(in0, in1);
                row_pairs(raw, lo, hi, st.expr[lo][i_out], st.expr[hi][i_out]);
            }
    count_pairs_into(st.freq_stat, raw);
}

// --------------------------------------------------------------- heuristics

constexpr PairC PAIR_NONE{-1, -1, false, 0};

inline PairC select_pair(const DAStateC& st, const std::string& method) {
    PairC best = PAIR_NONE;
    if (method == "dummy") return best;
    if (method == "mc") {
        int max_freq = 0;
        for (const auto& [p, c] : st.freq_stat)
            if (c >= max_freq) {
                max_freq = c;
                best = p;
            }
        return best;
    }
    if (method == "mc-dc" || method == "mc-pdc") {
        bool absolute = method == "mc-dc";
        double max_score = absolute ? 0.0 : -INF;
        for (const auto& [p, c] : st.freq_stat) {
            double score = c - 1e9 * std::fabs(st.ops[p.id0].latency - st.ops[p.id1].latency);
            if (score >= max_score) {
                max_score = score;
                best = p;
            }
        }
        return best;
    }
    if (method == "wmc") {
        double max_score = 0;
        for (const auto& [p, c] : st.freq_stat) {
            auto [n_overlap, _] = overlap_and_accum(st.ops[p.id0].qint, st.ops[p.id1].qint);
            double score = double(c) * n_overlap;
            if (score >= max_score) {
                max_score = score;
                best = p;
            }
        }
        return best;
    }
    if (method == "wmc-dc" || method == "wmc-pdc") {
        bool absolute = method == "wmc-dc";
        double max_score = absolute ? 0.0 : -INF;
        for (const auto& [p, c] : st.freq_stat) {
            auto [n_overlap, _] = overlap_and_accum(st.ops[p.id0].qint, st.ops[p.id1].qint);
            double score = double(c) * n_overlap - 256 * std::fabs(st.ops[p.id0].latency - st.ops[p.id1].latency);
            if (score >= max_score) {
                max_score = score;
                best = p;
            }
        }
        return best;
    }
    throw std::runtime_error("Unknown method: " + method);
}

// ------------------------------------------------------------------- core

inline DAStateC cmvm(const std::vector<double>& kernel, int n_in, int n_out, const std::string& method,
                     const std::vector<QInt>& qintervals, const std::vector<double>& latencies, int adder_size,
                     int carry_size) {
    DAStateC st = create_state(kernel, n_in, n_out, qintervals, latencies, method == "dummy");
    while (!st.freq_stat.empty()) {
        PairC pair = select_pair(st, method);
        if (pair.id0 == -1 || pair.id1 == -1) break;
        update_expr(st, pair, adder_size, carry_size);
        update_stats(st, pair);
    }
    return st;
}

inline int left_align(const QInt& q, int shift) {
    return int(std::log2(std::max(std::fabs(q.max + q.step), std::fabs(q.min)))) + shift;
}

// Heap key (lat, sub, left_align, qmin, qmax, qstep, id, shift) — identical
// total order to the host implementation, so reductions are decision-identical.
using HeapEntry = std::tuple<double, int, int, double, double, double, int, int>;

inline CombC to_solution(const DAStateC& st, int adder_size, int carry_size) {
    std::vector<OpC> ops = st.ops;
    CombC sol;
    sol.n_in = st.n_in;
    sol.n_out = st.n_out;
    sol.carry_size = carry_size;
    sol.adder_size = adder_size;
    sol.inp_shifts.assign(st.shift0.begin(), st.shift0.end());
    int n_expr = int(st.expr.size());
    int global_id = int(ops.size());

    for (int i_out = 0; i_out < st.n_out; ++i_out) {
        std::vector<int> idx, shifts, subs;
        for (int i_in = 0; i_in < n_expr; ++i_in)
            for (int v : st.expr[i_in][i_out]) {
                idx.push_back(i_in);
                shifts.push_back(to_shift(v));
                subs.push_back(to_sign(v) == -1 ? 1 : 0);
            }
        if (idx.size() == 1) {
            sol.out_shifts.push_back(st.shift1[i_out] + shifts[0]);
            sol.out_idxs.push_back(idx[0]);
            sol.out_negs.push_back(subs[0]);
            continue;
        }
        if (idx.empty()) {
            sol.out_idxs.push_back(-1);
            sol.out_shifts.push_back(st.shift1[i_out]);
            sol.out_negs.push_back(0);
            continue;
        }
        std::priority_queue<HeapEntry, std::vector<HeapEntry>, std::greater<HeapEntry>> heap;
        for (size_t k = 0; k < idx.size(); ++k) {
            const QInt& q = ops[idx[k]].qint;
            heap.emplace(ops[idx[k]].latency, subs[k], left_align(q, shifts[k]), q.min, q.max, q.step, idx[k], shifts[k]);
        }
        while (heap.size() > 1) {
            auto [lat0, sub0, la0, qmin0, qmax0, qstep0, id0, shift0] = heap.top();
            heap.pop();
            auto [lat1, sub1, la1, qmin1, qmax1, qstep1, id1, shift1] = heap.top();
            heap.pop();
            QInt q0{qmin0, qmax0, qstep0}, q1{qmin1, qmax1, qstep1};
            OpC op;
            int result_shift;
            if (sub0) {
                int s = shift0 - shift1;
                QInt q = qint_add(q1, q0, s, sub1 != 0, true);
                auto [dlat, dcost] = cost_add(q1, q0, s, (1 ^ sub1) != 0, adder_size, carry_size);
                op = OpC{id1, id0, 1 ^ sub1, s, q, std::max(lat0, lat1) + dlat, dcost};
                result_shift = shift1;
            } else {
                int s = shift1 - shift0;
                QInt q = qint_add(q0, q1, s, false, sub1 != 0);
                auto [dlat, dcost] = cost_add(q0, q1, s, sub1 != 0, adder_size, carry_size);
                op = OpC{id0, id1, sub1, s, q, std::max(lat0, lat1) + dlat, dcost};
                result_shift = shift0;
            }
            heap.emplace(op.latency, sub0 & sub1, left_align(op.qint, result_shift), op.qint.min, op.qint.max,
                         op.qint.step, global_id, result_shift);
            ops.push_back(op);
            ++global_id;
        }
        auto [flat, fsub, fla, fqmin, fqmax, fqstep, fid, fshift] = heap.top();
        sol.out_idxs.push_back(global_id - 1);
        sol.out_negs.push_back(fsub);
        sol.out_shifts.push_back(st.shift1[i_out] + fshift);
    }
    sol.ops = std::move(ops);
    return sol;
}

inline CombC solve_single(const std::vector<double>& kernel, int n_in, int n_out, const std::string& method,
                          const std::vector<QInt>& qintervals, const std::vector<double>& latencies, int adder_size,
                          int carry_size) {
    DAStateC st = cmvm(kernel, n_in, n_out, method, qintervals, latencies, adder_size, carry_size);
    return to_solution(st, adder_size, carry_size);
}

// -------------------------------------------------------------- decompose

// Prim's MST from root 0 with optional depth constraint (decompose.py).
inline std::vector<std::pair<int, int>> prim_mst_dc(const std::vector<int64_t>& cost_mat, int n, int dc) {
    constexpr int64_t BIG = (int64_t(1) << 62) / 2;
    std::vector<double> lat_mat(size_t(n) * n);
    for (int i = 0; i < n * n; ++i) lat_mat[i] = std::ceil(std::log2(double(std::max<int64_t>(cost_mat[i], 1))));
    std::vector<int> parent(n, -2);
    parent[0] = -1;
    std::vector<int64_t> latency(n, 0);
    std::vector<std::pair<int, int>> mapping;

    double _dc = -1.0;
    if (dc >= 0) {
        int64_t max_cost0 = 0;
        for (int j = 0; j < n; ++j) max_cost0 = std::max(max_cost0, cost_mat[j]);
        _dc = (std::ldexp(1.0, dc) - 1) + std::ceil(std::log2(double(max_cost0) + 1e-32));
    }

    for (int n_impl = 1; n_impl < n; ++n_impl) {
        std::vector<int> impl, not_impl;
        for (int i = 0; i < n; ++i) (parent[i] != -2 ? impl : not_impl).push_back(i);
        // row-major argmin with strict < matches numpy's first-minimum rule
        int64_t best = std::numeric_limits<int64_t>::max();
        int bi = -1, bj = -1;
        for (size_t a = 0; a < not_impl.size(); ++a)
            for (size_t b = 0; b < impl.size(); ++b) {
                int i = not_impl[a], j = impl[b];
                int64_t c = cost_mat[size_t(i) * n + j];
                if (dc >= 0) {
                    double max_lat = std::max(lat_mat[size_t(i) * n + j], double(latency[j])) + 1;
                    if (max_lat > _dc) c = BIG;
                }
                if (c < best) {
                    best = c;
                    bi = int(a);
                    bj = int(b);
                }
            }
        int i = not_impl[bi], j = impl[bj];
        parent[i] = j;
        mapping.emplace_back(j, i);
        latency[i] = int64_t(std::max(lat_mat[size_t(i) * n + j], double(latency[j])) + 1);
    }
    return mapping;
}

// W = m0 @ m1 split via MST over (centered) columns (decompose.py kernel_decompose).
inline void kernel_decompose(const std::vector<double>& kernel, int n_in, int n_out, int dc, std::vector<double>& m0,
                             std::vector<double>& m1, int& m0_cols) {
    std::vector<double> centered = kernel;
    std::vector<int> shift0, shift1;
    center(centered, n_in, n_out, shift0, shift1);

    if (dc == -1) {
        m0.assign(size_t(n_in) * n_out, 0.0);
        for (int i = 0; i < n_in; ++i)
            for (int j = 0; j < n_out; ++j) m0[size_t(i) * n_out + j] = std::ldexp(centered[size_t(i) * n_out + j], shift0[i]);
        m1.assign(size_t(n_out) * n_out, 0.0);
        for (int j = 0; j < n_out; ++j) m1[size_t(j) * n_out + j] = std::ldexp(1.0, shift1[j]);
        m0_cols = n_out;
        return;
    }

    int na = n_out + 1;  // augmented with zero root column 0
    auto aug = [&](int i, int j) -> double { return j == 0 ? 0.0 : centered[size_t(i) * n_out + (j - 1)]; };

    // pairwise distance = min CSD weight of (col_a - col_b) vs (col_a + col_b)
    std::vector<int64_t> dist(size_t(na) * na, 0), sign_arr(size_t(na) * na, 1);
    {
        std::vector<int64_t> d0(n_in), d1(n_in);
        for (int a = 0; a < na; ++a)
            for (int b = 0; b < na; ++b) {
                for (int i = 0; i < n_in; ++i) {
                    d0[i] = int64_t(aug(i, a) - aug(i, b));
                    d1[i] = int64_t(aug(i, a) + aug(i, b));
                }
                Csd c0 = int_arr_to_csd(d0), c1 = int_arr_to_csd(d1);
                int64_t w0 = 0, w1 = 0;
                for (int8_t v : c0.digits) w0 += v != 0;
                for (int8_t v : c1.digits) w1 += v != 0;
                sign_arr[size_t(a) * na + b] = (w1 - w0 < 0) ? -1 : 1;
                dist[size_t(a) * na + b] = std::min(w0, w1);
            }
    }

    auto mapping = prim_mst_dc(dist, na, dc);

    m0.assign(size_t(n_in) * n_out, 0.0);
    m1.assign(size_t(n_out) * n_out, 0.0);
    int cnt = 0;
    std::vector<double> col1(n_out);
    for (auto [_from, _to] : mapping) {
        int64_t sgn = sign_arr[size_t(_to) * na + _from];
        bool nonzero = false;
        std::vector<double> col0(n_in);
        for (int i = 0; i < n_in; ++i) {
            col0[i] = aug(i, _to) - aug(i, _from) * double(sgn);
            nonzero |= col0[i] != 0.0;
        }
        if (_from != 0)
            for (int r = 0; r < n_out; ++r) col1[r] = m1[size_t(r) * n_out + (_from - 1)] * double(sgn);
        else
            std::fill(col1.begin(), col1.end(), 0.0);
        if (nonzero) {
            col1[cnt] = 1.0;
            for (int i = 0; i < n_in; ++i) m0[size_t(i) * n_out + cnt] = col0[i];
            ++cnt;
        }
        for (int r = 0; r < n_out; ++r) m1[size_t(r) * n_out + (_to - 1)] = col1[r];
    }
    // apply factored-out scales: m0 rows by 2^shift0, m1 rows by 2^shift1 col-wise
    for (int i = 0; i < n_in; ++i)
        for (int j = 0; j < n_out; ++j) m0[size_t(i) * n_out + j] = std::ldexp(m0[size_t(i) * n_out + j], shift0[i]);
    for (int r = 0; r < n_out; ++r)
        for (int j = 0; j < n_out; ++j) m1[size_t(r) * n_out + j] = std::ldexp(m1[size_t(r) * n_out + j], shift1[j]);
    m0_cols = n_out;
}

// ---------------------------------------------------------------- driver

inline double minimal_latency(const std::vector<double>& kernel, int n_in, int n_out, const std::vector<QInt>& qintervals,
                              const std::vector<double>& latencies, int carry_size, int adder_size) {
    DAStateC st = create_state(kernel, n_in, n_out, qintervals, latencies, true);
    CombC sol = to_solution(st, adder_size, carry_size);
    return sol.max_out_latency();
}

inline bool ends_with_dc(const std::string& m) { return m.size() >= 2 && m.compare(m.size() - 2, 2, "dc") == 0; }

// One two-stage solve at a fixed decompose depth (cmvm/api.py _solve).
inline PipeC solve_fixed_dc(const std::vector<double>& kernel, int n_in, int n_out, std::string method0,
                            std::string method1, int64_t hard_dc, int64_t decompose_dc,
                            const std::vector<QInt>& qintervals, const std::vector<double>& latencies, int adder_size,
                            int carry_size) {
    if (method1 == "auto") method1 = (hard_dc >= 6 || ends_with_dc(method0)) ? method0 : method0 + "-dc";
    if (hard_dc == 0 && !ends_with_dc(method0)) method0 += "-dc";

    double min_lat = INF;
    if (hard_dc >= 0) min_lat = minimal_latency(kernel, n_in, n_out, qintervals, latencies, carry_size, adder_size);
    double latency_allowed = double(hard_dc) + min_lat;

    int64_t log2_n = int64_t(std::ceil(std::log2(double(n_in))));
    decompose_dc = decompose_dc == -2 ? std::min(hard_dc, log2_n) : std::min({hard_dc, decompose_dc, log2_n});

    while (true) {
        if (decompose_dc < 0 && hard_dc >= 0) {
            if (method0 != "dummy")
                method0 = method1 = "wmc-dc";
            else
                method0 = method1 = "dummy";
        }
        std::vector<double> m0, m1;
        int m0_cols = 0;
        kernel_decompose(kernel, n_in, n_out, int(decompose_dc), m0, m1, m0_cols);
        CombC sol0 = solve_single(m0, n_in, m0_cols, method0, qintervals, latencies, adder_size, carry_size);

        std::vector<QInt> q0 = sol0.out_qint();
        std::vector<double> l0 = sol0.out_latency();
        double max_lat0 = 0;
        for (double v : l0) max_lat0 = std::max(max_lat0, v);

        if (max_lat0 > latency_allowed) {
            if (!(method0 == "wmc-dc" && method1 == "wmc-dc") || decompose_dc >= 0) {
                --decompose_dc;
                continue;
            }
        }
        CombC sol1 = solve_single(m1, m0_cols, n_out, method1, q0, l0, adder_size, carry_size);
        if (sol1.max_out_latency() > latency_allowed) {
            if (!(method0 == "wmc-dc" && method1 == "wmc-dc") || decompose_dc >= 0) {
                --decompose_dc;
                continue;
            }
        }
        PipeC out;
        out.stages[0] = std::move(sol0);
        out.stages[1] = std::move(sol1);
        return out;
    }
}

// Full solve: OpenMP sweep over dc in [-1, min(hard_dc, ceil(log2 n_in))],
// argmin by total op cost (cmvm/api.py solve; reference api.cc:194-249).
inline PipeC solve(const std::vector<double>& kernel, int n_in, int n_out, const std::string& method0,
                   const std::string& method1, int64_t hard_dc, int64_t decompose_dc, const std::vector<QInt>& qintervals,
                   const std::vector<double>& latencies, int adder_size, int carry_size, bool search_all, int n_threads) {
    if (!search_all)
        return solve_fixed_dc(kernel, n_in, n_out, method0, method1, hard_dc, decompose_dc, qintervals, latencies,
                              adder_size, carry_size);

    int64_t h = hard_dc >= 0 ? hard_dc : 1000000000;
    int64_t max_dc = std::min<int64_t>(h, int64_t(std::ceil(std::log2(double(n_in)))));
    std::vector<int64_t> try_dcs;
    for (int64_t dc = -1; dc <= max_dc; ++dc) try_dcs.push_back(dc);

    std::vector<PipeC> results(try_dcs.size());
    std::vector<std::string> errors(try_dcs.size());
    int threads = n_threads > 0 ? n_threads : omp_get_max_threads();

#pragma omp parallel for schedule(dynamic) num_threads(threads)
    for (size_t t = 0; t < try_dcs.size(); ++t) {
        try {
            results[t] = solve_fixed_dc(kernel, n_in, n_out, method0, method1, h, try_dcs[t], qintervals, latencies,
                                        adder_size, carry_size);
        } catch (const std::exception& e) {
            errors[t] = e.what();
        }
    }
    for (const auto& e : errors)
        if (!e.empty()) throw std::runtime_error(e);

    size_t best = 0;
    double best_cost = INF;
    for (size_t t = 0; t < results.size(); ++t) {
        double c = results[t].cost();
        if (c < best_cost) {
            best_cost = c;
            best = t;
        }
    }
    return std::move(results[best]);
}

}  // namespace da4ml_cmvm

// ------------------------------------------------------------------ C ABI

#define DA4ML_API extern "C" __attribute__((visibility("default")))

namespace {
void copy_err(const std::string& msg, char* err, int64_t err_len) {
    if (!err || err_len <= 0) return;
    int64_t n = std::min<int64_t>(int64_t(msg.size()), err_len - 1);
    std::memcpy(err, msg.data(), size_t(n));
    err[n] = '\0';
}
}  // namespace

// Returns an opaque PipeC handle (free with cmvm_free), or NULL on error.
DA4ML_API void* cmvm_solve(const double* kernel, int64_t n_in, int64_t n_out, const char* method0, const char* method1,
                           int64_t hard_dc, int64_t decompose_dc, const double* qintervals /* n_in x 3 */,
                           const double* latencies /* n_in */, int64_t adder_size, int64_t carry_size,
                           int64_t search_all, int64_t n_threads, char* err, int64_t err_len) {
    try {
        std::vector<double> k(kernel, kernel + n_in * n_out);
        std::vector<da4ml_cmvm::QInt> qints(static_cast<size_t>(n_in));
        for (int64_t i = 0; i < n_in; ++i)
            qints[i] = da4ml_cmvm::QInt{qintervals[i * 3], qintervals[i * 3 + 1], qintervals[i * 3 + 2]};
        std::vector<double> lats(latencies, latencies + n_in);
        auto* res = new da4ml_cmvm::PipeC(da4ml_cmvm::solve(k, int(n_in), int(n_out), method0, method1, hard_dc,
                                                            decompose_dc, qints, lats, int(adder_size), int(carry_size),
                                                            search_all != 0, int(n_threads)));
        return res;
    } catch (const std::exception& e) {
        copy_err(e.what(), err, err_len);
        return nullptr;
    }
}

// Stage geometry: n_in, n_out, n_ops of stage 0 or 1.
DA4ML_API int cmvm_stage_shape(void* handle, int64_t stage, int64_t* n_in, int64_t* n_out, int64_t* n_ops) {
    if (!handle || stage < 0 || stage > 1) return 1;
    const auto& s = static_cast<da4ml_cmvm::PipeC*>(handle)->stages[stage];
    *n_in = s.n_in;
    *n_out = s.n_out;
    *n_ops = int64_t(s.ops.size());
    return 0;
}

// Fill caller-allocated buffers: ops as n_ops x 9 doubles
// [id0, id1, opcode, data, qmin, qmax, qstep, latency, cost].
DA4ML_API int cmvm_stage_fill(void* handle, int64_t stage, double* ops9, int32_t* inp_shifts, int32_t* out_idxs,
                              int32_t* out_shifts, int32_t* out_negs) {
    if (!handle || stage < 0 || stage > 1) return 1;
    const auto& s = static_cast<da4ml_cmvm::PipeC*>(handle)->stages[stage];
    for (size_t i = 0; i < s.ops.size(); ++i) {
        const auto& op = s.ops[i];
        double* row = ops9 + i * 9;
        row[0] = op.id0;
        row[1] = op.id1;
        row[2] = op.opcode;
        row[3] = double(op.data);
        row[4] = op.qint.min;
        row[5] = op.qint.max;
        row[6] = op.qint.step;
        row[7] = op.latency;
        row[8] = op.cost;
    }
    std::copy(s.inp_shifts.begin(), s.inp_shifts.end(), inp_shifts);
    std::copy(s.out_idxs.begin(), s.out_idxs.end(), out_idxs);
    std::copy(s.out_shifts.begin(), s.out_shifts.end(), out_shifts);
    std::copy(s.out_negs.begin(), s.out_negs.end(), out_negs);
    return 0;
}

DA4ML_API void cmvm_free(void* handle) { delete static_cast<da4ml_cmvm::PipeC*>(handle); }

// ---------------------------------------------------- JAX-backend host side
//
// The device search (cmvm/jax_search.py) returns per-lane greedy *decisions*
// (op records) and final CSD digit tensors; rebuilding f64 op metadata and
// running the adder-tree emission (to_solution) is the host-side tail. These
// batched entry points run that tail in C++ with OpenMP over lanes.

// geo: n_lanes x 4 int64 = (ni, no, nb, n_add). Flat per-lane data follows
// the same lane order with implicit prefix offsets:
//   shift0s: ni int32        shift1s: no int32
//   qints:   ni x 3 f64      lats:    ni f64
//   E:       (ni+n_add) x no x nb int8 (digit in {-1,0,+1})
//   recs:    n_add x 4 int32 = (id0, id1, sub, shift), lane-local ids
// Returns an opaque std::vector<CombC>* (free with cmvm_emit_free).
DA4ML_API void* cmvm_emit_batch(int64_t n_lanes, const int64_t* geo, const int32_t* shift0s, const int32_t* shift1s,
                                const double* qints, const double* lats, const int8_t* E, const int32_t* recs,
                                int64_t adder_size, int64_t carry_size, int64_t n_threads, char* err, int64_t err_len) {
    using namespace da4ml_cmvm;
    try {
        std::vector<int64_t> off_in(n_lanes + 1, 0), off_out(n_lanes + 1, 0), off_E(n_lanes + 1, 0),
            off_rec(n_lanes + 1, 0);
        for (int64_t l = 0; l < n_lanes; ++l) {
            int64_t ni = geo[l * 4], no = geo[l * 4 + 1], nb = geo[l * 4 + 2], na = geo[l * 4 + 3];
            off_in[l + 1] = off_in[l] + ni;
            off_out[l + 1] = off_out[l] + no;
            off_E[l + 1] = off_E[l] + (ni + na) * no * nb;
            off_rec[l + 1] = off_rec[l] + na;
        }
        auto* out = new std::vector<CombC>(size_t(n_lanes));
        std::vector<std::string> errors(static_cast<size_t>(n_lanes));
        int threads = n_threads > 0 ? int(n_threads) : omp_get_max_threads();
#pragma omp parallel for schedule(dynamic) num_threads(threads)
        for (int64_t l = 0; l < n_lanes; ++l) {
            try {
                int ni = int(geo[l * 4]), no = int(geo[l * 4 + 1]), nb = int(geo[l * 4 + 2]), na = int(geo[l * 4 + 3]);
                DAStateC st;
                st.n_in = ni;
                st.n_out = no;
                st.n_bits = nb;
                st.shift0.assign(shift0s + off_in[l], shift0s + off_in[l] + ni);
                st.shift1.assign(shift1s + off_out[l], shift1s + off_out[l] + no);
                const double* q = qints + off_in[l] * 3;
                const double* la = lats + off_in[l];
                for (int i = 0; i < ni; ++i) {
                    double sf = std::ldexp(1.0, st.shift0[i]);
                    st.ops.push_back(
                        OpC{i, -1, -1, 0, QInt{q[i * 3] * sf, q[i * 3 + 1] * sf, q[i * 3 + 2] * sf}, la[i], 0.0});
                }
                const int32_t* r = recs + off_rec[l] * 4;
                for (int t = 0; t < na; ++t) {
                    PairC p{r[t * 4], r[t * 4 + 1], r[t * 4 + 2] != 0, r[t * 4 + 3]};
                    st.ops.push_back(pair_to_op(p, st, int(adder_size), int(carry_size)));
                }
                const int8_t* e = E + off_E[l];
                st.expr.resize(size_t(ni + na));
                for (int p = 0; p < ni + na; ++p) {
                    st.expr[p].resize(no);
                    for (int io = 0; io < no; ++io) {
                        auto& digits = st.expr[p][io];
                        for (int b = 0; b < nb; ++b) {
                            int8_t v = e[(size_t(p) * no + io) * nb + b];
                            if (v != 0) digits.push_back(encode_digit(b, v));
                        }
                    }
                }
                (*out)[l] = to_solution(st, int(adder_size), int(carry_size));
            } catch (const std::exception& ex) {
                errors[l] = ex.what();
            }
        }
        for (const auto& e : errors)
            if (!e.empty()) {
                delete out;
                copy_err(e, err, err_len);
                return nullptr;
            }
        return out;
    } catch (const std::exception& e) {
        copy_err(e.what(), err, err_len);
        return nullptr;
    }
}

DA4ML_API int cmvm_emit_shape(void* handle, int64_t lane, int64_t* n_in, int64_t* n_out, int64_t* n_ops) {
    if (!handle) return 1;
    auto& v = *static_cast<std::vector<da4ml_cmvm::CombC>*>(handle);
    if (lane < 0 || size_t(lane) >= v.size()) return 1;
    *n_in = v[lane].n_in;
    *n_out = v[lane].n_out;
    *n_ops = int64_t(v[lane].ops.size());
    return 0;
}

DA4ML_API int cmvm_emit_fill(void* handle, int64_t lane, double* ops9, int32_t* inp_shifts, int32_t* out_idxs,
                             int32_t* out_shifts, int32_t* out_negs) {
    if (!handle) return 1;
    auto& v = *static_cast<std::vector<da4ml_cmvm::CombC>*>(handle);
    if (lane < 0 || size_t(lane) >= v.size()) return 1;
    const auto& s = v[lane];
    for (size_t i = 0; i < s.ops.size(); ++i) {
        const auto& op = s.ops[i];
        double* row = ops9 + i * 9;
        row[0] = op.id0;
        row[1] = op.id1;
        row[2] = op.opcode;
        row[3] = double(op.data);
        row[4] = op.qint.min;
        row[5] = op.qint.max;
        row[6] = op.qint.step;
        row[7] = op.latency;
        row[8] = op.cost;
    }
    std::copy(s.inp_shifts.begin(), s.inp_shifts.end(), inp_shifts);
    std::copy(s.out_idxs.begin(), s.out_idxs.end(), out_idxs);
    std::copy(s.out_shifts.begin(), s.out_shifts.end(), out_shifts);
    std::copy(s.out_negs.begin(), s.out_negs.end(), out_negs);
    return 0;
}

DA4ML_API void cmvm_emit_free(void* handle) { delete static_cast<std::vector<da4ml_cmvm::CombC>*>(handle); }

// Batched kernel decomposition: lane l reads kernels[koff[l] .. koff[l]+ni*no)
// (row-major ni x no) and writes m0 (ni x no) / m1 (no x no) at the same
// layout into m0_out/m1_out (caller-allocated, same offsets / no*no offsets).
DA4ML_API int cmvm_decompose_batch(int64_t n_lanes, const int64_t* geo /* n_lanes x 3: ni,no,dc */,
                                   const double* kernels, double* m0_out, double* m1_out, int64_t n_threads, char* err,
                                   int64_t err_len) {
    using namespace da4ml_cmvm;
    try {
        std::vector<int64_t> off_k(n_lanes + 1, 0), off_m1(n_lanes + 1, 0);
        for (int64_t l = 0; l < n_lanes; ++l) {
            int64_t ni = geo[l * 3], no = geo[l * 3 + 1];
            off_k[l + 1] = off_k[l] + ni * no;
            off_m1[l + 1] = off_m1[l] + no * no;
        }
        std::vector<std::string> errors(static_cast<size_t>(n_lanes));
        int threads = n_threads > 0 ? int(n_threads) : omp_get_max_threads();
#pragma omp parallel for schedule(dynamic) num_threads(threads)
        for (int64_t l = 0; l < n_lanes; ++l) {
            try {
                int ni = int(geo[l * 3]), no = int(geo[l * 3 + 1]), dc = int(geo[l * 3 + 2]);
                std::vector<double> k(kernels + off_k[l], kernels + off_k[l + 1]);
                std::vector<double> m0, m1;
                int m0_cols = 0;
                kernel_decompose(k, ni, no, dc, m0, m1, m0_cols);
                std::copy(m0.begin(), m0.end(), m0_out + off_k[l]);
                std::copy(m1.begin(), m1.end(), m1_out + off_m1[l]);
            } catch (const std::exception& ex) {
                errors[l] = ex.what();
            }
        }
        for (const auto& e : errors)
            if (!e.empty()) {
                copy_err(e, err, err_len);
                return 1;
            }
        return 0;
    } catch (const std::exception& e) {
        copy_err(e.what(), err, err_len);
        return 1;
    }
}
