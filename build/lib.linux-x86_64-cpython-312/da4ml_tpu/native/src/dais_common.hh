// Shared integer semantics for the native DAIS interpreter.
//
// Bit-exact with the Python/NumPy reference backend
// (da4ml_tpu/runtime/numpy_backend.py) and, transitively, with the reference
// C++ interpreter semantics (reference: src/da4ml/_binary/dais/
// DAISInterpreter.cc): two's-complement int64, arithmetic shifts, modular
// wrap into the annotated width.
#pragma once

#include <cmath>
#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace da4ml {

// v << s for s >= 0, arithmetic v >> -s otherwise. Left shifts go through
// uint64 so overflow wraps mod 2^64 (matching NumPy int64) instead of UB.
inline int64_t shl(int64_t v, int64_t s) {
    if (s >= 0) {
        if (s >= 64) return 0;
        return static_cast<int64_t>(static_cast<uint64_t>(v) << s);
    }
    s = -s;
    if (s >= 64) return v < 0 ? -1 : 0;
    return v >> s;
}

// Two's-complement wrap of v into `width` bits; unsigned wrap when !is_signed.
// Equivalent to ((v - int_min) mod 2^width) + int_min with Python modulo.
inline int64_t wrap(int64_t v, bool is_signed, int64_t width) {
    if (width <= 0) return 0;
    if (width >= 64) return v;
    const uint64_t mask = (uint64_t(1) << width) - 1;
    uint64_t u = static_cast<uint64_t>(v) & mask;
    if (is_signed && ((u >> (width - 1)) & 1)) u |= ~mask;
    return static_cast<int64_t>(u);
}

inline int64_t quantize(int64_t v, int64_t f_from, bool signed_to, int64_t width_to, int64_t f_to) {
    return wrap(shl(v, f_to - f_from), signed_to, width_to);
}

// MSB of the two's-complement representation: sign bit for signed values,
// top data bit for unsigned ones.
inline bool msb(int64_t v, bool is_signed, int64_t width) {
    if (is_signed) return v < 0;
    if (width <= 0) return false;
    if (width >= 64) return v < 0;  // top bit of the 64-bit pattern
    return v >= (int64_t(1) << (width - 1));
}

// Decoded DAIS program, struct-of-arrays (mirrors ir/dais_binary.py).
struct DaisProgram {
    int32_t n_in = 0, n_out = 0, n_ops = 0, n_tables = 0;
    std::vector<int32_t> inp_shifts, out_idxs, out_shifts, out_negs;
    std::vector<int32_t> opcode, id0, id1, data_lo, data_hi, is_signed, integers, fractionals;
    std::vector<std::vector<int32_t>> tables;

    int32_t width(int i) const { return is_signed[i] + integers[i] + fractionals[i]; }

    // Parse the flat int32 DAIS v1 stream (spec: docs/dais.md in this repo).
    static DaisProgram from_binary(const int32_t* bin, int64_t len) {
        if (len < 6) throw std::runtime_error("Binary data too small to contain a DAIS program");
        if (bin[0] != 1) throw std::runtime_error("DAIS version mismatch: expected 1, got " + std::to_string(bin[0]));
        DaisProgram p;
        p.n_in = bin[2];
        p.n_out = bin[3];
        p.n_ops = bin[4];
        p.n_tables = bin[5];
        int64_t need = 6 + p.n_in + 3 * int64_t(p.n_out) + 8 * int64_t(p.n_ops) + p.n_tables;
        if (len < need) throw std::runtime_error("Binary truncated");
        int64_t off = 6;
        auto take = [&](std::vector<int32_t>& dst, int64_t n) {
            dst.assign(bin + off, bin + off + n);
            off += n;
        };
        take(p.inp_shifts, p.n_in);
        take(p.out_idxs, p.n_out);
        take(p.out_shifts, p.n_out);
        take(p.out_negs, p.n_out);
        p.opcode.resize(p.n_ops);
        p.id0.resize(p.n_ops);
        p.id1.resize(p.n_ops);
        p.data_lo.resize(p.n_ops);
        p.data_hi.resize(p.n_ops);
        p.is_signed.resize(p.n_ops);
        p.integers.resize(p.n_ops);
        p.fractionals.resize(p.n_ops);
        for (int i = 0; i < p.n_ops; ++i) {
            const int32_t* row = bin + off + 8 * int64_t(i);
            p.opcode[i] = row[0];
            p.id0[i] = row[1];
            p.id1[i] = row[2];
            p.data_lo[i] = row[3];
            p.data_hi[i] = row[4];
            p.is_signed[i] = row[5];
            p.integers[i] = row[6];
            p.fractionals[i] = row[7];
        }
        off += 8 * int64_t(p.n_ops);
        if (p.n_tables > 0) {
            std::vector<int32_t> sizes;
            take(sizes, p.n_tables);
            for (int t = 0; t < p.n_tables; ++t) {
                if (off + sizes[t] > len) throw std::runtime_error("Binary truncated in tables");
                p.tables.emplace_back(bin + off, bin + off + sizes[t]);
                off += sizes[t];
            }
        }
        if (off != len) throw std::runtime_error("Binary size mismatch");
        p.validate();
        return p;
    }

    // Causality + width validation (reference: DAISInterpreter.cc:429-457).
    void validate() const {
        for (int i = 0; i < n_ops; ++i) {
            if (opcode[i] != -1 && id0[i] >= i)
                throw std::runtime_error("Causality violation on id0 at op " + std::to_string(i));
            if (id1[i] >= i) throw std::runtime_error("Causality violation on id1 at op " + std::to_string(i));
            if ((opcode[i] == 6 || opcode[i] == -6) && data_lo[i] >= i)
                throw std::runtime_error("Causality violation on mux condition index at op " + std::to_string(i));
            if (width(i) > 63) throw std::runtime_error("Op width exceeds 63 bits at op " + std::to_string(i));
        }
        for (int j = 0; j < n_out; ++j)
            if (out_idxs[j] >= n_ops) throw std::runtime_error("Output index out of range");
    }
};

// Execute the program for one sample. `buf` must hold n_ops slots.
inline void exec_sample(const DaisProgram& p, const double* inp, int64_t* buf, double* out) {
    const int n_ops = p.n_ops;
    for (int i = 0; i < n_ops; ++i) {
        const int oc = p.opcode[i];
        const int i0 = p.id0[i], i1 = p.id1[i];
        const int32_t dlo = p.data_lo[i], dhi = p.data_hi[i];
        const bool sg = p.is_signed[i];
        const int f = p.fractionals[i];
        const int w = p.width(i);
        int64_t r = 0;
        switch (oc) {
            case -1: {
                double scaled = std::ldexp(inp[i0], p.inp_shifts[i0] + f);
                r = wrap(static_cast<int64_t>(std::floor(scaled)), sg, w);
                break;
            }
            case 0:
            case 1: {
                const int f0 = p.fractionals[i0], f1 = p.fractionals[i1];
                const int64_t actual_shift = int64_t(dlo) + f0 - f1;
                int64_t v1 = buf[i0];
                int64_t v2 = oc == 1 ? -buf[i1] : buf[i1];
                int64_t s = actual_shift > 0 ? v1 + shl(v2, actual_shift) : shl(v1, -actual_shift) + v2;
                const int64_t global_shift = std::max<int64_t>(f0, f1 - dlo) - f;
                r = global_shift > 0 ? (s >> global_shift) : s;
                break;
            }
            case 2:
            case -2: {
                int64_t v = oc == -2 ? -buf[i0] : buf[i0];
                int64_t q = quantize(v, p.fractionals[i0], sg, w, f);
                r = v < 0 ? 0 : q;
                break;
            }
            case 3:
            case -3: {
                int64_t v = oc == -3 ? -buf[i0] : buf[i0];
                r = quantize(v, p.fractionals[i0], sg, w, f);
                break;
            }
            case 4: {
                const int64_t shift = int64_t(f) - p.fractionals[i0];
                const int64_t c = (int64_t(dhi) << 32) | int64_t(uint32_t(dlo));
                r = shl(buf[i0], shift) + c;
                break;
            }
            case 5:
                r = (int64_t(dhi) << 32) | int64_t(uint32_t(dlo));
                break;
            case 6:
            case -6: {
                const int ic = dlo;
                const int f0 = p.fractionals[i0], f1 = p.fractionals[i1];
                const int64_t shift1 = int64_t(f) - f1 + dhi;
                const int64_t shift0 = int64_t(f) - f0;
                const bool cond = msb(buf[ic], p.is_signed[ic], p.width(ic));
                int64_t v1 = oc == -6 ? -buf[i1] : buf[i1];
                r = cond ? wrap(shl(buf[i0], shift0), sg, w) : wrap(shl(v1, shift1), sg, w);
                break;
            }
            case 7:
                r = buf[i0] * buf[i1];
                break;
            case 8: {
                const int t = dlo;
                const auto& table = p.tables[t];
                const bool sg0 = p.is_signed[i0];
                const int w0 = p.width(i0);
                const int64_t zero = sg0 ? -(int64_t(1) << (w0 - 1)) : 0;
                const int64_t index = buf[i0] - zero - dhi;
                if (index < 0 || index >= int64_t(table.size()))
                    throw std::runtime_error("Logic lookup index out of bounds at op " + std::to_string(i));
                r = table[size_t(index)];
                break;
            }
            case 9:
            case -9: {
                int64_t v = oc == -9 ? -buf[i0] : buf[i0];
                const int w0 = p.width(i0);
                const int64_t mask = w0 >= 64 ? -1 : (int64_t(1) << w0) - 1;
                if (dlo == 0)
                    r = sg ? ~v : (~v) & mask;
                else if (dlo == 1)
                    r = v != 0;
                else if (dlo == 2)
                    r = (v & mask) == mask;
                else
                    throw std::runtime_error("Unknown bit unary op");
                break;
            }
            case 10: {
                const int f0 = p.fractionals[i0], f1 = p.fractionals[i1];
                const int64_t actual_shift = int64_t(dlo) + f0 - f1;
                int64_t v1 = buf[i0], v2 = buf[i1];
                if (dhi & 1) v1 = -v1;
                if (dhi & 2) v2 = -v2;
                if (actual_shift > 0)
                    v2 = shl(v2, actual_shift);
                else
                    v1 = shl(v1, -actual_shift);
                const int subop = dhi >> 24;
                if (subop == 0)
                    r = v1 & v2;
                else if (subop == 1)
                    r = v1 | v2;
                else if (subop == 2)
                    r = v1 ^ v2;
                else
                    throw std::runtime_error("Unknown bit binary op");
                break;
            }
            default:
                throw std::runtime_error("Unknown opcode " + std::to_string(oc));
        }
        buf[i] = r;
    }
    for (int j = 0; j < p.n_out; ++j) {
        const int idx = p.out_idxs[j];
        if (idx < 0) {
            out[j] = 0.0;
            continue;
        }
        int64_t v = buf[idx];
        if (p.out_negs[j]) v = -v;
        out[j] = std::ldexp(double(v), p.out_shifts[j] - p.fractionals[idx]);
    }
}

}  // namespace da4ml
