"""Code generation backends: RTL (Verilog/VHDL) and HLS C++ projects.

Parity target: reference src/da4ml/codegen/__init__.py (RTLModel,
VerilogModel, VHDLModel, HLSModel).
"""

from .rtl.rtl_model import RTLModel, VerilogModel, VHDLModel

__all__ = ['RTLModel', 'VerilogModel', 'VHDLModel']

try:  # HLS backend lands in its own milestone
    from .hls.hls_model import HLSModel  # noqa: F401

    __all__.append('HLSModel')
except ImportError:
    pass
