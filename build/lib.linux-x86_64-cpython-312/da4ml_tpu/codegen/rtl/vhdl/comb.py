"""VHDL-2008 emitter for one CombLogic stage — structural twin of the
Verilog emitter (same layout, primitives and .mem files; entity
instantiations instead of module instances).

Parity target: reference src/da4ml/codegen/rtl/vhdl/comb.py.
"""

from __future__ import annotations

from ..verilog.comb import VerilogCombEmitter, _i32


def _bits(value: int, width: int) -> str:
    """Two's-complement binary string literal of `value` in `width` bits."""
    return format(int(value) & ((1 << width) - 1), f'0{width}b')


class VHDLCombEmitter(VerilogCombEmitter):
    """Emit one combinational VHDL entity for a CombLogic stage.

    Reuses the Verilog emitter's layout/table machinery; overrides all text
    generation. Signal declarations are collected separately (VHDL requires
    them in the architecture declarative region).
    """

    def __init__(self, comb, name: str, print_latency: bool = False):
        super().__init__(comb, name, print_latency)
        self._decls: list[str] = []
        self._stmts: list[str] = []

    # ------------------------------------------------------------- helpers

    def _decl_sig(self, name: str, width: int, kind: str = 'std_logic_vector'):
        self._decls.append(f'    signal {name} : {kind}({width - 1} downto 0);')

    def _vinst(self, prim: str, n: int, params: dict, ports: dict):
        g = ', '.join(f'{k} => {v}' for k, v in params.items())
        p = ', '.join(f'{k} => {v}' for k, v in ports.items())
        lat = f'  -- latency={self.comb.ops[n].latency}' if self.print_latency else ''
        self._stmts.append(f'    i{n} : entity work.{prim} generic map ({g}) port map ({p});{lat}')

    def _ext_expr(self, src: str, signed: int, width: int) -> str:
        if signed:
            return f'resize(signed({src}), {width})'
        return f'signed(resize(unsigned({src}), {width}))'

    # ------------------------------------------------------------ op walk

    def _emit_op(self, n: int):
        comb, op = self.comb, self.comb.ops[n]
        oc = op.opcode
        k, i, f = self.kifs[n]
        w = self.widths[n]
        if w == 0:
            return

        def kw(idx):
            kk, ii, ff = self.kifs[idx]
            return int(kk), self.widths[idx], ff

        self._decl_sig(f'v{n}', w)

        if oc == -1:
            off, width = self.input_layout()[op.id0]
            self._stmts.append(f'    v{n} <= inp({off + width - 1} downto {off});')
        elif oc in (0, 1):
            s0, w0, f0 = kw(op.id0)
            s1, w1, f1 = kw(op.id1)
            s = int(op.data) + f0 - f1
            gshift = max(max(f0, f1 - int(op.data)) - f, 0)
            self._vinst(
                'shift_adder',
                n,
                dict(WA=w0, SA=s0, WB=w1, SB=s1, SHA=max(-s, 0), SHB=max(s, 0), SUB_OP=int(oc == 1), GSHIFT=gshift, WO=w),
                dict(a=f'v{op.id0}', b=f'v{op.id1}', o=f'v{n}'),
            )
        elif oc in (2, -2):
            s0, w0, f0 = kw(op.id0)
            self._vinst(
                'relu',
                n,
                dict(WA=w0, SA=s0, NEG=int(oc == -2), SHIFT_N=f - f0, WO=w),
                dict(a=f'v{op.id0}', o=f'v{n}'),
            )
        elif oc in (3, -3):
            s0, w0, f0 = kw(op.id0)
            self._vinst(
                'quantizer',
                n,
                dict(WA=w0, SA=s0, NEG=int(oc == -3), SHIFT_N=f - f0, WO=w),
                dict(a=f'v{op.id0}', o=f'v{n}'),
            )
        elif oc == 4:
            s0, w0, f0 = kw(op.id0)
            shift = f - f0
            shl, shr = max(shift, 0), max(-shift, 0)
            wi = max(w0, w + shr) + shl + 2
            self._decl_sig(f'ca{n}', wi, 'signed')
            self._decl_sig(f'cr{n}', wi, 'signed')
            self._stmts.append(f'    ca{n} <= {self._ext_expr(f"v{op.id0}", s0, wi)};')
            self._stmts.append(
                f'    cr{n} <= shift_right(shift_left(ca{n}, {shl}), {shr}) + signed\'("{_bits(int(op.data), wi)}");'
            )
            self._stmts.append(f'    v{n} <= std_logic_vector(cr{n}({w - 1} downto 0));')
        elif oc == 5:
            self._stmts.append(f'    v{n} <= "{_bits(int(op.data), w)}";')
        elif oc in (6, -6):
            ic = int(op.data) & 0xFFFFFFFF
            dhi = _i32(int(op.data) >> 32)
            sc, wc, _ = kw(ic)
            s0, w0, f0 = kw(op.id0)
            s1, w1, f1 = kw(op.id1)
            self._vinst(
                'msb_mux',
                n,
                dict(WC=wc, WA=w0, SA=s0, WB=w1, SB=s1, NEG_B=int(oc == -6), SH0=f - f0, SH1=f - f1 + dhi, WO=w),
                dict(c=f'v{ic}', a=f'v{op.id0}', b=f'v{op.id1}', o=f'v{n}'),
            )
        elif oc == 7:
            s0, w0, _ = kw(op.id0)
            s1, w1, _ = kw(op.id1)
            self._vinst(
                'multiplier',
                n,
                dict(WA=w0, SA=s0, WB=w1, SB=s1, WO=w),
                dict(a=f'v{op.id0}', b=f'v{op.id1}', o=f'v{n}'),
            )
        elif oc == 8:
            _, w0, _ = kw(op.id0)
            memfile = self._table_memfile(int(op.data), op.id0, w)
            self._vinst(
                'lookup_table',
                n,
                dict(WA=w0, WO=w, MEMFILE=f'"{memfile}"'),
                dict(a=f'v{op.id0}', o=f'v{n}'),
            )
        elif oc in (9, -9):
            s0, w0, _ = kw(op.id0)
            self._vinst(
                'bit_unary',
                n,
                dict(WA=w0, SA=s0, W0=w0, NEG=int(oc == -9), OP=int(op.data), WO=w),
                dict(a=f'v{op.id0}', o=f'v{n}'),
            )
        elif oc == 10:
            s0, w0, f0 = kw(op.id0)
            s1, w1, f1 = kw(op.id1)
            data = int(op.data)
            shift = _i32(data) + f0 - f1
            self._vinst(
                'bit_binop',
                n,
                dict(
                    WA=w0,
                    SA=s0,
                    WB=w1,
                    SB=s1,
                    NEG_A=(data >> 32) & 1,
                    NEG_B=(data >> 33) & 1,
                    SHA=max(-shift, 0),
                    SHB=max(shift, 0),
                    OP=(data >> 56) & 0xFF,
                    WO=w,
                ),
                dict(a=f'v{op.id0}', b=f'v{op.id1}', o=f'v{n}'),
            )
        else:
            raise ValueError(f'Unknown opcode {oc} in op {n}')

    def emit(self) -> str:
        comb = self.comb
        rc = comb.ref_count
        self._decls, self._stmts = [], []
        for n in range(len(comb.ops)):
            if rc[n] == 0:
                continue
            self._emit_op(n)

        out_lay = self.output_layout()
        neg_emitted: dict[tuple[int, int], str] = {}
        for j, (idx, neg) in enumerate(zip(comb.out_idxs, comb.out_negs)):
            off, w = out_lay[j]
            if w == 0:
                continue
            sl = f'out_port({off + w - 1} downto {off})'
            if idx < 0 or self.widths[idx] == 0:
                self._stmts.append(f"    {sl} <= (others => '0');")
                continue
            if not neg:
                self._stmts.append(f'    {sl} <= v{idx};')
            else:
                key = (idx, w)
                if key not in neg_emitted:
                    k0, _, _ = self.kifs[idx]
                    self._decl_sig(f'vneg{idx}_{w}', w)
                    self._vinst(
                        'negative',
                        len(comb.ops) + j,
                        dict(WA=self.widths[idx], SA=int(k0), WO=w),
                        dict(a=f'v{idx}', o=f'vneg{idx}_{w}'),
                    )
                    neg_emitted[key] = f'vneg{idx}_{w}'
                self._stmts.append(f'    {sl} <= {neg_emitted[key]};')

        header = [
            f'-- Generated by da4ml_tpu: combinational DAIS stage {self.name}',
            'library ieee;',
            'use ieee.std_logic_1164.all;',
            'use ieee.numeric_std.all;',
            '',
            f'entity {self.name} is',
            '    port (',
            f'        inp : in std_logic_vector({max(self.total_in - 1, 0)} downto 0);',
            f'        out_port : out std_logic_vector({max(self.total_out - 1, 0)} downto 0)',
            '    );',
            'end entity;',
            '',
            f'architecture rtl of {self.name} is',
        ]
        return '\n'.join(header + self._decls + ['begin'] + self._stmts + ['end architecture;']) + '\n'
