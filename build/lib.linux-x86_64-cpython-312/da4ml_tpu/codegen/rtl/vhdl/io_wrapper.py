"""VHDL uniform-lane IO wrapper (twin of verilog/io_wrapper.py).

Parity target: reference src/da4ml/codegen/rtl/vhdl/io_wrapper.py.
"""

from __future__ import annotations

from ....ir.comb import CombLogic, Pipeline
from ..verilog.io_wrapper import IOMap, hetero_io_map


def emit_io_wrapper_vhdl(model: CombLogic | Pipeline, name: str, inner: str, clocked: bool) -> tuple[str, IOMap, IOMap]:
    in_map = hetero_io_map(model.inp_qint)
    out_map = hetero_io_map(model.out_qint)
    lw_in, lw_out = in_map.lane_width, out_map.lane_width
    packed_in = sum(w for _, w, _, _ in in_map.elems)
    packed_out = sum(w for _, w, _, _ in out_map.elems)

    decls = [
        f'    signal p_in : std_logic_vector({max(packed_in - 1, 0)} downto 0);',
        f'    signal p_out : std_logic_vector({max(packed_out - 1, 0)} downto 0);',
    ]
    stmts = []
    for e, (off, w, _sg, _f) in enumerate(in_map.elems):
        if w == 0:
            continue
        stmts.append(f'    p_in({off + w - 1} downto {off}) <= inp({e * lw_in + w - 1} downto {e * lw_in});')
    port_assoc = 'clk => clk, ' if clocked else ''
    stmts.append(f'    core : entity work.{inner} port map ({port_assoc}inp => p_in, out_port => p_out);')
    for e, (off, w, sg, _f) in enumerate(out_map.elems):
        hi, lo = (e + 1) * lw_out - 1, e * lw_out
        if w == 0:
            stmts.append(f"    out_port({hi} downto {lo}) <= (others => '0');")
        elif w == lw_out:
            stmts.append(f'    out_port({hi} downto {lo}) <= p_out({off + w - 1} downto {off});')
        else:
            fill = f'p_out({off + w - 1})' if sg else "'0'"
            stmts.append(f'    out_port({hi} downto {lo + w}) <= (others => {fill});')
            stmts.append(f'    out_port({lo + w - 1} downto {lo}) <= p_out({off + w - 1} downto {off});')

    clk_port = '        clk : in std_logic;\n' if clocked else ''
    text = '\n'.join(
        [
            f'-- Uniform-lane IO wrapper for {inner}',
            'library ieee;',
            'use ieee.std_logic_1164.all;',
            '',
            f'entity {name} is',
            '    port (',
            clk_port + f'        inp : in std_logic_vector({max(in_map.total_uniform - 1, 0)} downto 0);',
            f'        out_port : out std_logic_vector({max(out_map.total_uniform - 1, 0)} downto 0)',
            '    );',
            'end entity;',
            '',
            f'architecture rtl of {name} is',
            *decls,
            'begin',
            *stmts,
            'end architecture;',
        ]
    )
    return text + '\n', in_map, out_map
