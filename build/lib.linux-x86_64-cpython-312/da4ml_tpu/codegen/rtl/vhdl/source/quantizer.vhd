-- Fixed-point re-quantization (DAIS opcode +/-3): o = wrap((+/-a) << SHIFT_N).
library ieee;
use ieee.std_logic_1164.all;
use ieee.numeric_std.all;
use work.da4ml_util.all;

entity quantizer is
    generic (WA : integer := 8; SA : integer := 1; NEG : integer := 0; SHIFT_N : integer := 0; WO : integer := 8);
    port (
        a : in std_logic_vector(WA - 1 downto 0);
        o : out std_logic_vector(WO - 1 downto 0)
    );
end entity;

architecture rtl of quantizer is
    function shl_n return integer is
    begin
        if SHIFT_N > 0 then
            return SHIFT_N;
        end if;
        return 0;
    end function;
    function shr_n return integer is
    begin
        if SHIFT_N < 0 then
            return -SHIFT_N;
        end if;
        return 0;
    end function;
    constant SHL : integer := shl_n;
    constant SHR : integer := shr_n;
    constant WI : integer := imax(WA, WO + SHR) + SHL + 1;
    signal ea, v, shifted : signed(WI - 1 downto 0);
begin
    ea <= ext(a, SA, WI);
    v <= -ea when NEG = 1 else ea;
    shifted <= shift_right(shift_left(v, SHL), SHR);
    o <= std_logic_vector(shifted(WO - 1 downto 0));
end architecture;
