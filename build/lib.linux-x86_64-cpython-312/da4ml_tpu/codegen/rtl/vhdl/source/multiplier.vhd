-- o = a * b (DAIS opcode 7), low WO bits of the full product.
library ieee;
use ieee.std_logic_1164.all;
use ieee.numeric_std.all;
use work.da4ml_util.all;

entity multiplier is
    generic (WA : integer := 8; SA : integer := 1; WB : integer := 8; SB : integer := 1; WO : integer := 16);
    port (
        a : in std_logic_vector(WA - 1 downto 0);
        b : in std_logic_vector(WB - 1 downto 0);
        o : out std_logic_vector(WO - 1 downto 0)
    );
end entity;

architecture rtl of multiplier is
    constant WI : integer := WA + WB + 2;
    signal ea, eb : signed(WI - 1 downto 0);
    signal prod : signed(2 * WI - 1 downto 0);
begin
    ea <= ext(a, SA, WI);
    eb <= ext(b, SB, WI);
    prod <= ea * eb;
    o <= std_logic_vector(prod(WO - 1 downto 0));
end architecture;
