-- ROM lookup (DAIS opcode 8): o = rom(a). The .mem file uses the same
-- padded/rolled layout as the Verilog twin; entries are read with textio.
library ieee;
use ieee.std_logic_1164.all;
use ieee.numeric_std.all;
use std.textio.all;

entity lookup_table is
    generic (WA : integer := 8; WO : integer := 8; MEMFILE : string := "table.mem");
    port (
        a : in std_logic_vector(WA - 1 downto 0);
        o : out std_logic_vector(WO - 1 downto 0)
    );
end entity;

architecture rtl of lookup_table is
    type rom_t is array (0 to 2 ** WA - 1) of std_logic_vector(WO - 1 downto 0);

    impure function load_rom return rom_t is
        file f : text open read_mode is MEMFILE;
        variable l : line;
        variable entry : std_logic_vector(WO - 1 downto 0);
        variable rom : rom_t := (others => (others => 'X'));
        variable idx : integer := 0;
        variable ok : boolean;
    begin
        while not endfile(f) and idx < 2 ** WA loop
            readline(f, l);
            hread(l, entry, ok);
            if ok then
                rom(idx) := entry;
            end if;
            idx := idx + 1;
        end loop;
        return rom;
    end function;

    constant rom : rom_t := load_rom;
begin
    o <= rom(to_integer(unsigned(a)));
end architecture;
