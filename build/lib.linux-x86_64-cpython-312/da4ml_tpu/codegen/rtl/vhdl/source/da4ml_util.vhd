-- Shared helpers for the da4ml_tpu VHDL primitive library: integer max and
-- sign-aware resize (sign-extend when S=1, zero-extend otherwise).
library ieee;
use ieee.std_logic_1164.all;
use ieee.numeric_std.all;

package da4ml_util is
    function imax(a : integer; b : integer) return integer;
    function ext(v : std_logic_vector; s : integer; w : integer) return signed;
end package;

package body da4ml_util is
    function imax(a : integer; b : integer) return integer is
    begin
        if a > b then
            return a;
        end if;
        return b;
    end function;

    function ext(v : std_logic_vector; s : integer; w : integer) return signed is
    begin
        if s = 1 then
            return resize(signed(v), w);
        end if;
        return signed(resize(unsigned(v), w));
    end function;
end package body;
