-- o = -a, sign/zero-extended to WO bits before negation.
library ieee;
use ieee.std_logic_1164.all;
use ieee.numeric_std.all;
use work.da4ml_util.all;

entity negative is
    generic (WA : integer := 8; SA : integer := 1; WO : integer := 9);
    port (
        a : in std_logic_vector(WA - 1 downto 0);
        o : out std_logic_vector(WO - 1 downto 0)
    );
end entity;

architecture rtl of negative is
    constant WI : integer := imax(WO, WA) + 1;
    signal ea, neg : signed(WI - 1 downto 0);
begin
    ea <= ext(a, SA, WI);
    neg <= -ea;
    o <= std_logic_vector(neg(WO - 1 downto 0));
end architecture;
