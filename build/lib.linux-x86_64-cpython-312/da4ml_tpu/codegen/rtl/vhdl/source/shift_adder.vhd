-- o = ((a' << SHA) +/- (b' << SHB)) >>> GSHIFT, truncated to WO bits.
-- VHDL twin of verilog/source/shift_adder.v (same parameterization).
library ieee;
use ieee.std_logic_1164.all;
use ieee.numeric_std.all;
use work.da4ml_util.all;

entity shift_adder is
    generic (
        WA : integer := 8;
        SA : integer := 1;
        WB : integer := 8;
        SB : integer := 1;
        SHA : integer := 0;
        SHB : integer := 0;
        SUB_OP : integer := 0;
        GSHIFT : integer := 0;
        WO : integer := 8
    );
    port (
        a : in std_logic_vector(WA - 1 downto 0);
        b : in std_logic_vector(WB - 1 downto 0);
        o : out std_logic_vector(WO - 1 downto 0)
    );
end entity;

architecture rtl of shift_adder is
    constant WI : integer := imax(imax(WA + SHA + 1, WB + SHB + 1), WO + GSHIFT) + 1;
    signal ea, eb, total, shifted : signed(WI - 1 downto 0);
begin
    ea <= ext(a, SA, WI);
    eb <= ext(b, SB, WI);
    total <= shift_left(ea, SHA) - shift_left(eb, SHB) when SUB_OP = 1
             else shift_left(ea, SHA) + shift_left(eb, SHB);
    shifted <= shift_right(total, GSHIFT);
    o <= std_logic_vector(shifted(WO - 1 downto 0));
end architecture;
