-- Bitwise unary op (DAIS opcode +/-9) on v = +/-a:
-- OP=0 NOT (WO bits), OP=1 OR-reduce (v /= 0), OP=2 AND-reduce over W0 bits.
library ieee;
use ieee.std_logic_1164.all;
use ieee.numeric_std.all;
use work.da4ml_util.all;

entity bit_unary is
    generic (
        WA : integer := 8;
        SA : integer := 1;
        W0 : integer := 8;
        NEG : integer := 0;
        OP : integer := 0;
        WO : integer := 8
    );
    port (
        a : in std_logic_vector(WA - 1 downto 0);
        o : out std_logic_vector(WO - 1 downto 0)
    );
end entity;

architecture rtl of bit_unary is
    constant WI : integer := imax(WA, WO) + 2;
    signal ea, v, r : signed(WI - 1 downto 0);
    signal vw : std_logic_vector(W0 - 1 downto 0);
begin
    ea <= ext(a, SA, WI);
    v <= -ea when NEG = 1 else ea;
    vw <= std_logic_vector(v(W0 - 1 downto 0));
    g_not : if OP = 0 generate
        r <= not v;
        o <= std_logic_vector(r(WO - 1 downto 0));
    end generate;
    g_any : if OP = 1 generate
        o <= std_logic_vector(to_unsigned(1, WO)) when unsigned(vw) /= 0
             else std_logic_vector(to_unsigned(0, WO));
        r <= (others => '0');
    end generate;
    g_all : if OP = 2 generate
        -- VHDL-2008 unary reduction
        o <= std_logic_vector(to_unsigned(1, WO)) when (and vw) = '1'
             else std_logic_vector(to_unsigned(0, WO));
        r <= (others => '0');
    end generate;
end architecture;
