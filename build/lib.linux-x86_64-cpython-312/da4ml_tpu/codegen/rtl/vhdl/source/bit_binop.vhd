-- Bitwise binary op (DAIS opcode 10): o = ((+/-a) << SHA) OP ((+/-b) << SHB),
-- OP in {AND=0, OR=1, XOR=2}, over two's-complement WO bits.
library ieee;
use ieee.std_logic_1164.all;
use ieee.numeric_std.all;
use work.da4ml_util.all;

entity bit_binop is
    generic (
        WA : integer := 8;
        SA : integer := 1;
        WB : integer := 8;
        SB : integer := 1;
        NEG_A : integer := 0;
        NEG_B : integer := 0;
        SHA : integer := 0;
        SHB : integer := 0;
        OP : integer := 0;
        WO : integer := 8
    );
    port (
        a : in std_logic_vector(WA - 1 downto 0);
        b : in std_logic_vector(WB - 1 downto 0);
        o : out std_logic_vector(WO - 1 downto 0)
    );
end entity;

architecture rtl of bit_binop is
    constant WI : integer := imax(WA + SHA, WB + SHB) + 2;
    signal ea0, eb0, ea, eb, r : signed(WI - 1 downto 0);
begin
    ea0 <= ext(a, SA, WI);
    eb0 <= ext(b, SB, WI);
    ea <= shift_left(-ea0, SHA) when NEG_A = 1 else shift_left(ea0, SHA);
    eb <= shift_left(-eb0, SHB) when NEG_B = 1 else shift_left(eb0, SHB);
    r <= (ea and eb) when OP = 0 else (ea or eb) when OP = 1 else (ea xor eb);
    o <= std_logic_vector(r(WO - 1 downto 0));
end architecture;
