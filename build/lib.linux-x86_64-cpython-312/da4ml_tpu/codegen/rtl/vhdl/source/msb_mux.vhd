-- MSB-select mux (DAIS opcode +/-6): sel = top bit of c;
-- o = sel ? wrap(a << SH0) : wrap((+/-b) << SH1).
library ieee;
use ieee.std_logic_1164.all;
use ieee.numeric_std.all;
use work.da4ml_util.all;

entity msb_mux is
    generic (
        WC : integer := 8;
        WA : integer := 8;
        SA : integer := 1;
        WB : integer := 8;
        SB : integer := 1;
        NEG_B : integer := 0;
        SH0 : integer := 0;
        SH1 : integer := 0;
        WO : integer := 8
    );
    port (
        c : in std_logic_vector(WC - 1 downto 0);
        a : in std_logic_vector(WA - 1 downto 0);
        b : in std_logic_vector(WB - 1 downto 0);
        o : out std_logic_vector(WO - 1 downto 0)
    );
end entity;

architecture rtl of msb_mux is
    function pos_part(s : integer) return integer is
    begin
        if s > 0 then
            return s;
        end if;
        return 0;
    end function;
    constant SHL0 : integer := pos_part(SH0);
    constant SHR0 : integer := pos_part(-SH0);
    constant SHL1 : integer := pos_part(SH1);
    constant SHR1 : integer := pos_part(-SH1);
    constant WI0 : integer := imax(WA, WO + SHR0) + SHL0 + 1;
    constant WI1 : integer := imax(WB, WO + SHR1) + SHL1 + 2;
    signal ea, r0 : signed(WI0 - 1 downto 0);
    signal eb0, eb, r1 : signed(WI1 - 1 downto 0);
begin
    ea <= ext(a, SA, WI0);
    eb0 <= ext(b, SB, WI1);
    eb <= -eb0 when NEG_B = 1 else eb0;
    r0 <= shift_right(shift_left(ea, SHL0), SHR0);
    r1 <= shift_right(shift_left(eb, SHL1), SHR1);
    o <= std_logic_vector(r0(WO - 1 downto 0)) when c(WC - 1) = '1' else std_logic_vector(r1(WO - 1 downto 0));
end architecture;
