// Bitwise binary op (DAIS opcode 10): o = (+/-a << SHA) OP (+/-b << SHB)
// with OP in {AND=0, OR=1, XOR=2}, computed over WO-bit two's complement.
module bit_binop #(
    parameter WA = 8,
    parameter SA = 1,
    parameter WB = 8,
    parameter SB = 1,
    parameter NEG_A = 0,
    parameter NEG_B = 0,
    parameter SHA = 0,
    parameter SHB = 0,
    parameter OP = 0,
    parameter WO = 8
) (
    input  [WA-1:0] a,
    input  [WB-1:0] b,
    output [WO-1:0] o
);
    localparam WI = (WA + SHA > WB + SHB ? WA + SHA : WB + SHB) + 2;
    wire signed [WI-1:0] ea0 = SA ? $signed(a) : $signed({1'b0, a});
    wire signed [WI-1:0] eb0 = SB ? $signed(b) : $signed({1'b0, b});
    wire signed [WI-1:0] ea = (NEG_A ? -ea0 : ea0) <<< SHA;
    wire signed [WI-1:0] eb = (NEG_B ? -eb0 : eb0) <<< SHB;
    wire signed [WI-1:0] r = OP == 0 ? (ea & eb) : OP == 1 ? (ea | eb) : (ea ^ eb);
    assign o = r[WO-1:0];
endmodule
