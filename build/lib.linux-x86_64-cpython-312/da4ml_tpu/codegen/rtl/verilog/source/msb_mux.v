// MSB-select mux (DAIS opcode +/-6): sel = MSB of c (sign bit for signed,
// top data bit for unsigned — the same physical bit either way);
// o = sel ? wrap(a << SH0) : wrap((+/-b) << SH1).
module msb_mux #(
    parameter WC = 8,
    parameter WA = 8,
    parameter SA = 1,
    parameter WB = 8,
    parameter SB = 1,
    parameter NEG_B = 0,
    parameter SH0 = 0,
    parameter SH1 = 0,
    parameter WO = 8
) (
    input  [WC-1:0] c,
    input  [WA-1:0] a,
    input  [WB-1:0] b,
    output [WO-1:0] o
);
    localparam SHL0 = SH0 > 0 ? SH0 : 0;
    localparam SHR0 = SH0 < 0 ? -SH0 : 0;
    localparam SHL1 = SH1 > 0 ? SH1 : 0;
    localparam SHR1 = SH1 < 0 ? -SH1 : 0;
    localparam WI0 = (WA > WO + SHR0 ? WA : WO + SHR0) + SHL0 + 1;
    localparam WI1 = (WB > WO + SHR1 ? WB : WO + SHR1) + SHL1 + 2;

    wire signed [WI0-1:0] ea = SA ? $signed(a) : $signed({1'b0, a});
    wire signed [WI1-1:0] eb0 = SB ? $signed(b) : $signed({1'b0, b});
    wire signed [WI1-1:0] eb = NEG_B ? -eb0 : eb0;
    wire signed [WI0-1:0] r0 = (ea <<< SHL0) >>> SHR0;
    wire signed [WI1-1:0] r1 = (eb <<< SHL1) >>> SHR1;
    assign o = c[WC-1] ? r0[WO-1:0] : r1[WO-1:0];
endmodule
