// o = -a, sign/zero-extended to WO bits before negation (two's complement).
module negative #(
    parameter WA = 8,
    parameter SA = 1,
    parameter WO = 9
) (
    input  [WA-1:0] a,
    output [WO-1:0] o
);
    localparam WI = (WO > WA ? WO : WA) + 1;
    wire signed [WI-1:0] ea = SA ? $signed(a) : $signed({1'b0, a});
    wire signed [WI-1:0] neg = -ea;
    assign o = neg[WO-1:0];
endmodule
