// Rectifier with re-quantization (DAIS opcode +/-2): v = +/-a;
// o = v < 0 ? 0 : wrap(v << SHIFT) with SHIFT = f_out - f_in.
module relu #(
    parameter WA = 8,
    parameter SA = 1,
    parameter NEG = 0,
    parameter SHIFT = 0,
    parameter WO = 8
) (
    input  [WA-1:0] a,
    output [WO-1:0] o
);
    localparam SHL = SHIFT > 0 ? SHIFT : 0;
    localparam SHR = SHIFT < 0 ? -SHIFT : 0;
    localparam WI = (WA > WO + SHR ? WA : WO + SHR) + SHL + 2;
    wire signed [WI-1:0] ea = SA ? $signed(a) : $signed({1'b0, a});
    wire signed [WI-1:0] v = NEG ? -ea : ea;
    wire signed [WI-1:0] shifted = (v <<< SHL) >>> SHR;
    assign o = v[WI-1] ? {WO{1'b0}} : shifted[WO-1:0];
endmodule
