// o = ((a' << SHA) +/- (b' << SHB)) >>> GSHIFT, truncated to WO bits.
// a'/b' are sign- (SA/SB=1) or zero-extended operands. Arithmetic matches the
// DAIS shift-add semantics (da4ml_tpu/runtime/numpy_backend.py, opcode 0/1):
// low WO bits are exact under two's-complement wrap.
module shift_adder #(
    parameter WA = 8,
    parameter SA = 1,
    parameter WB = 8,
    parameter SB = 1,
    parameter SHA = 0,
    parameter SHB = 0,
    parameter SUB = 0,
    parameter GSHIFT = 0,
    parameter WO = 8
) (
    input  [WA-1:0] a,
    input  [WB-1:0] b,
    output [WO-1:0] o
);
    // internal width: enough for both shifted operands, the carry, and the
    // bits consumed by the final arithmetic right shift
    localparam WSA = WA + SHA + 1;
    localparam WSB = WB + SHB + 1;
    localparam WMX = WSA > WSB ? WSA : WSB;
    localparam WI  = (WMX > WO + GSHIFT ? WMX : WO + GSHIFT) + 1;

    wire signed [WI-1:0] ea = SA ? $signed(a) : $signed({1'b0, a});
    wire signed [WI-1:0] eb = SB ? $signed(b) : $signed({1'b0, b});
    wire signed [WI-1:0] sum = SUB ? (ea <<< SHA) - (eb <<< SHB) : (ea <<< SHA) + (eb <<< SHB);
    wire signed [WI-1:0] shifted = sum >>> GSHIFT;
    assign o = shifted[WO-1:0];
endmodule
