// Bitwise unary op (DAIS opcode +/-9) on v = +/-a (wrapped to W0 bits):
// OP=0 NOT (over WO bits), OP=1 OR-reduce (v != 0), OP=2 AND-reduce (&v[W0]).
module bit_unary #(
    parameter WA = 8,
    parameter SA = 1,
    parameter W0 = 8,
    parameter NEG = 0,
    parameter OP = 0,
    parameter WO = 8
) (
    input  [WA-1:0] a,
    output [WO-1:0] o
);
    localparam WI = (WA > WO ? WA : WO) + 2;
    wire signed [WI-1:0] ea = SA ? $signed(a) : $signed({1'b0, a});
    wire signed [WI-1:0] v = NEG ? -ea : ea;
    wire [W0-1:0] vw = v[W0-1:0];
    generate
        if (OP == 0) begin : g_not
            wire signed [WI-1:0] r = ~v;
            assign o = r[WO-1:0];
        end else if (OP == 1) begin : g_any
            assign o = |vw;  // implicit zero-extension to WO bits
        end else begin : g_all
            assign o = &vw;
        end
    endgenerate
endmodule
