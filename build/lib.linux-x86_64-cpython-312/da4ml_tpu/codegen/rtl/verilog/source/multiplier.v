// o = a * b (DAIS opcode 7), low WO bits of the full product.
module multiplier #(
    parameter WA = 8,
    parameter SA = 1,
    parameter WB = 8,
    parameter SB = 1,
    parameter WO = 16
) (
    input  [WA-1:0] a,
    input  [WB-1:0] b,
    output [WO-1:0] o
);
    localparam WI = WA + WB + 2;
    wire signed [WI-1:0] ea = SA ? $signed(a) : $signed({1'b0, a});
    wire signed [WI-1:0] eb = SB ? $signed(b) : $signed({1'b0, b});
    wire signed [WI-1:0] prod = ea * eb;
    assign o = prod[WO-1:0];
endmodule
