// ROM lookup (DAIS opcode 8): o = rom[a]. The .mem file is padded/rolled so
// the raw two's-complement bits of the key index directly (unreachable
// entries hold 'x'). rom_style hint lets synthesis pick LUTROM/BRAM.
module lookup_table #(
    parameter WA = 8,
    parameter WO = 8,
    parameter MEMFILE = "table.mem"
) (
    input  [WA-1:0] a,
    output [WO-1:0] o
);
    (* rom_style = "distributed" *) reg [WO-1:0] rom [0:(1 << WA)-1];
    initial $readmemh(MEMFILE, rom);
    assign o = rom[a];
endmodule
