// Fixed-point re-quantization (DAIS opcode +/-3, TRN/WRAP): o = wrap(+/-a << SHIFT)
// with SHIFT = f_out - f_in (negative SHIFT is an arithmetic right shift).
module quantizer #(
    parameter WA = 8,
    parameter SA = 1,
    parameter NEG = 0,
    parameter SHIFT = 0,
    parameter WO = 8
) (
    input  [WA-1:0] a,
    output [WO-1:0] o
);
    localparam SHL = SHIFT > 0 ? SHIFT : 0;
    localparam SHR = SHIFT < 0 ? -SHIFT : 0;
    localparam WI = (WA > WO + SHR ? WA : WO + SHR) + SHL + 1;
    wire signed [WI-1:0] ea = SA ? $signed(a) : $signed({1'b0, a});
    wire signed [WI-1:0] v = NEG ? -ea : ea;
    wire signed [WI-1:0] shifted = (v <<< SHL) >>> SHR;
    assign o = shifted[WO-1:0];
endmodule
