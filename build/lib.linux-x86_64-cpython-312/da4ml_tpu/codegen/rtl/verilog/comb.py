"""Verilog emitter for one CombLogic stage.

Each live SSA op becomes a wire plus a primitive instantiation (shift_adder /
quantizer / relu / msb_mux / multiplier / lookup_table / bit_binop /
bit_unary / negative from ``source/``); dead ops (ref_count 0) are skipped.
Ports are flat bit vectors packing the heterogeneous per-element fixed-point
formats back to back (LSB first).

Structural parity with the reference's emitter: src/da4ml/codegen/rtl/
verilog/comb.py (SSA walk, negation dedup, sha-named .mem files with 'x'
for unreachable entries).
"""

from __future__ import annotations

from math import ceil

import numpy as np

from ....ir.comb import CombLogic
from ....ir.types import minimal_kif


def _i32(x: int) -> int:
    return ((int(x) & 0xFFFFFFFF) + (1 << 31)) % (1 << 32) - (1 << 31)


def _hex_entry(value: float, width: int) -> str:
    """One $readmemh entry: two's-complement hex, 'x' for unreachable (NaN)."""
    digits = max(ceil(width / 4), 1)
    if np.isnan(value):
        return 'x' * digits
    return format(int(value) & ((1 << width) - 1), f'0{digits}x')


class VerilogCombEmitter:
    """Emit one combinational module for a CombLogic stage."""

    def __init__(self, comb: CombLogic, name: str, print_latency: bool = False):
        self.comb = comb
        self.name = name
        self.print_latency = print_latency
        self.kifs = [minimal_kif(op.qint) for op in comb.ops]
        self.widths = [k + i + f for k, i, f in self.kifs]
        self.mem_files: dict[str, str] = {}
        self._table_mem: dict[int, str] = {}

    # -------------------------------------------------------------- layout

    def input_layout(self) -> list[tuple[int, int]]:
        """(offset, width) per input index, LSB-first packing."""
        widths = [0] * self.comb.shape[0]
        for n, op in enumerate(self.comb.ops):
            if op.opcode == -1:
                widths[op.id0] = self.widths[n]
        out, off = [], 0
        for w in widths:
            out.append((off, w))
            off += w
        return out

    def output_layout(self) -> list[tuple[int, int]]:
        out, off = [], 0
        for qi in self.comb.out_qint:
            k, i, f = minimal_kif(qi)
            w = k + i + f
            out.append((off, w))
            off += w
        return out

    @property
    def total_in(self) -> int:
        lay = self.input_layout()
        return lay[-1][0] + lay[-1][1] if lay else 0

    @property
    def total_out(self) -> int:
        lay = self.output_layout()
        return lay[-1][0] + lay[-1][1] if lay else 0

    # ------------------------------------------------------------ emission

    def _inst(self, prim: str, n: int, params: dict, ports: dict) -> str:
        p = ', '.join(f'.{k}({v})' for k, v in params.items())
        io = ', '.join(f'.{k}({v})' for k, v in ports.items())
        lat = f'  // latency={self.comb.ops[n].latency}' if self.print_latency else ''
        return f'    {prim} #({p}) i{n} ({io});{lat}'

    def _op_lines(self, n: int, rc) -> list[str]:
        comb, op = self.comb, self.comb.ops[n]
        oc = op.opcode
        k, i, f = self.kifs[n]
        w = self.widths[n]
        if w == 0:
            return [f'    wire v{n}_zero = 1\'b0;']  # zero-width value, never read as data
        decl = f'    wire [{w - 1}:0] v{n};'
        lines = [decl]

        def kw(idx):  # (signed, width, frac) of an operand
            kk, ii, ff = self.kifs[idx]
            return int(kk), self.widths[idx], ff

        if oc == -1:
            off, width = self.input_layout()[op.id0]
            lines.append(f'    assign v{n} = inp[{off + width - 1}:{off}];')
        elif oc in (0, 1):
            s0, w0, f0 = kw(op.id0)
            s1, w1, f1 = kw(op.id1)
            s = int(op.data) + f0 - f1
            gshift = max(max(f0, f1 - int(op.data)) - f, 0)
            lines.append(
                self._inst(
                    'shift_adder',
                    n,
                    dict(WA=w0, SA=s0, WB=w1, SB=s1, SHA=max(-s, 0), SHB=max(s, 0), SUB=int(oc == 1), GSHIFT=gshift, WO=w),
                    dict(a=f'v{op.id0}', b=f'v{op.id1}', o=f'v{n}'),
                )
            )
        elif oc in (2, -2):
            s0, w0, f0 = kw(op.id0)
            lines.append(
                self._inst(
                    'relu',
                    n,
                    dict(WA=w0, SA=s0, NEG=int(oc == -2), SHIFT=f - f0, WO=w),
                    dict(a=f'v{op.id0}', o=f'v{n}'),
                )
            )
        elif oc in (3, -3):
            s0, w0, f0 = kw(op.id0)
            lines.append(
                self._inst(
                    'quantizer',
                    n,
                    dict(WA=w0, SA=s0, NEG=int(oc == -3), SHIFT=f - f0, WO=w),
                    dict(a=f'v{op.id0}', o=f'v{n}'),
                )
            )
        elif oc == 4:
            s0, w0, f0 = kw(op.id0)
            shift = f - f0
            shl, shr = max(shift, 0), max(-shift, 0)
            wi = max(w0, w + shr) + shl + 2
            c = int(op.data)
            lit = f"-{wi}'sd{-c}" if c < 0 else f"{wi}'sd{c}"
            ext = f'$signed(v{op.id0})' if s0 else f"$signed({{1'b0, v{op.id0}}})"
            lines.append(f'    wire signed [{wi - 1}:0] ca{n} = {ext};')
            lines.append(f'    wire signed [{wi - 1}:0] cr{n} = ((ca{n} <<< {shl}) >>> {shr}) + {lit};')
            lines.append(f'    assign v{n} = cr{n}[{w - 1}:0];')
        elif oc == 5:
            c = int(op.data) & ((1 << w) - 1)
            lines.append(f"    assign v{n} = {w}'d{c};")
        elif oc in (6, -6):
            ic = int(op.data) & 0xFFFFFFFF
            dhi = _i32(int(op.data) >> 32)
            sc, wc, _ = kw(ic)
            s0, w0, f0 = kw(op.id0)
            s1, w1, f1 = kw(op.id1)
            lines.append(
                self._inst(
                    'msb_mux',
                    n,
                    dict(
                        WC=wc,
                        WA=w0,
                        SA=s0,
                        WB=w1,
                        SB=s1,
                        NEG_B=int(oc == -6),
                        SH0=f - f0,
                        SH1=f - f1 + dhi,
                        WO=w,
                    ),
                    dict(c=f'v{ic}', a=f'v{op.id0}', b=f'v{op.id1}', o=f'v{n}'),
                )
            )
        elif oc == 7:
            s0, w0, _ = kw(op.id0)
            s1, w1, _ = kw(op.id1)
            lines.append(
                self._inst(
                    'multiplier',
                    n,
                    dict(WA=w0, SA=s0, WB=w1, SB=s1, WO=w),
                    dict(a=f'v{op.id0}', b=f'v{op.id1}', o=f'v{n}'),
                )
            )
        elif oc == 8:
            assert comb.lookup_tables is not None
            table = comb.lookup_tables[int(op.data)]
            _, w0, _ = kw(op.id0)
            memfile = self._table_memfile(int(op.data), op.id0, w)
            lines.append(
                self._inst(
                    'lookup_table',
                    n,
                    dict(WA=w0, WO=w, MEMFILE=f'"{memfile}"'),
                    dict(a=f'v{op.id0}', o=f'v{n}'),
                )
            )
        elif oc in (9, -9):
            s0, w0, _ = kw(op.id0)
            lines.append(
                self._inst(
                    'bit_unary',
                    n,
                    dict(WA=w0, SA=s0, W0=w0, NEG=int(oc == -9), OP=int(op.data), WO=w),
                    dict(a=f'v{op.id0}', o=f'v{n}'),
                )
            )
        elif oc == 10:
            s0, w0, f0 = kw(op.id0)
            s1, w1, f1 = kw(op.id1)
            data = int(op.data)
            shift = _i32(data) + f0 - f1
            subop = (data >> 56) & 0xFF
            lines.append(
                self._inst(
                    'bit_binop',
                    n,
                    dict(
                        WA=w0,
                        SA=s0,
                        WB=w1,
                        SB=s1,
                        NEG_A=(data >> 32) & 1,
                        NEG_B=(data >> 33) & 1,
                        SHA=max(-shift, 0),
                        SHB=max(shift, 0),
                        OP=subop,
                        WO=w,
                    ),
                    dict(a=f'v{op.id0}', b=f'v{op.id1}', o=f'v{n}'),
                )
            )
        else:
            raise ValueError(f'Unknown opcode {oc} in op {n}')
        return lines

    def _table_memfile(self, t_idx: int, key_op: int, out_width: int) -> str:
        if t_idx in self._table_mem:
            return self._table_mem[t_idx]
        assert self.comb.lookup_tables is not None
        table = self.comb.lookup_tables[t_idx]
        key_qint = self.comb.ops[key_op].qint
        padded = table.padded_table(key_qint)
        fname = f'lut_{table.spec.hash[:16]}.mem'
        self.mem_files[fname] = '\n'.join(_hex_entry(v, out_width) for v in padded) + '\n'
        self._table_mem[t_idx] = fname
        return fname

    def emit(self) -> str:
        comb = self.comb
        rc = comb.ref_count
        lines = [
            f'// Generated by da4ml_tpu: combinational DAIS stage {self.name}',
            f'module {self.name} (',
            f'    input  [{max(self.total_in - 1, 0)}:0] inp,',
            f'    output [{max(self.total_out - 1, 0)}:0] out',
            ');',
        ]
        for n in range(len(comb.ops)):
            if rc[n] == 0:
                continue
            lines.extend(self._op_lines(n, rc))

        out_lay = self.output_layout()
        neg_emitted: dict[int, str] = {}
        for j, (idx, neg) in enumerate(zip(comb.out_idxs, comb.out_negs)):
            off, w = out_lay[j]
            if w == 0:
                continue
            sl = f'out[{off + w - 1}:{off}]'
            if idx < 0 or self.widths[idx] == 0:
                lines.append(f"    assign {sl} = {w}'d0;")
                continue
            if not neg:
                assert w == self.widths[idx], f'output {j}: width {w} != op width {self.widths[idx]}'
                lines.append(f'    assign {sl} = v{idx};')
            else:
                if idx not in neg_emitted:
                    k0, _, _ = self.kifs[idx]
                    lines.append(f'    wire [{w - 1}:0] vneg{idx};')
                    lines.append(
                        self._inst(
                            'negative',
                            len(comb.ops) + j,
                            dict(WA=self.widths[idx], SA=int(k0), WO=w),
                            dict(a=f'v{idx}', o=f'vneg{idx}'),
                        )
                    )
                    neg_emitted[idx] = f'vneg{idx}'
                lines.append(f'    assign {sl} = {neg_emitted[idx]};')
        lines.append('endmodule')
        return '\n'.join(lines) + '\n'
