"""Heterogeneous-to-uniform IO mapping and the wrapper module.

``hetero_io_map`` packs per-element fixed-point lanes (k, i, f each) into
uniform max-width lanes with sign/zero extension, so external logic can
address element ``e`` at ``e * lane_width`` without knowing the per-element
formats. Parity target: reference src/da4ml/codegen/rtl/verilog/
io_wrapper.py (hetero_io_map).
"""

from __future__ import annotations

from dataclasses import dataclass

from ....ir.comb import CombLogic, Pipeline
from ....ir.types import minimal_kif


@dataclass
class IOMap:
    lane_width: int
    # per element: (packed_offset, width, signed, frac)
    elems: list[tuple[int, int, bool, int]]

    @property
    def n_lanes(self) -> int:
        return len(self.elems)

    @property
    def total_uniform(self) -> int:
        return self.lane_width * len(self.elems)


def hetero_io_map(qints) -> IOMap:
    elems, off = [], 0
    lane = 1
    for qi in qints:
        k, i, f = minimal_kif(qi)
        w = k + i + f
        elems.append((off, w, bool(k), f))
        off += w
        lane = max(lane, w)
    return IOMap(lane_width=lane, elems=elems)


def emit_io_wrapper(model: CombLogic | Pipeline, name: str, inner: str, clocked: bool) -> tuple[str, IOMap, IOMap]:
    """Wrapper exposing uniform lanes around the packed inner module."""
    in_map = hetero_io_map(model.inp_qint)
    out_map = hetero_io_map(model.out_qint)
    lw_in, lw_out = in_map.lane_width, out_map.lane_width

    lines = [
        f'// Uniform-lane IO wrapper for {inner}',
        f'module {name} (',
    ]
    if clocked:
        lines.append('    input clk,')
    lines.append(f'    input  [{max(in_map.total_uniform - 1, 0)}:0] inp,')
    lines.append(f'    output [{max(out_map.total_uniform - 1, 0)}:0] out')
    lines.append(');')

    packed_in = sum(w for _, w, _, _ in in_map.elems)
    packed_out = sum(w for _, w, _, _ in out_map.elems)
    lines.append(f'    wire [{max(packed_in - 1, 0)}:0] p_in;')
    lines.append(f'    wire [{max(packed_out - 1, 0)}:0] p_out;')
    for e, (off, w, _sg, _f) in enumerate(in_map.elems):
        if w == 0:
            continue
        lines.append(f'    assign p_in[{off + w - 1}:{off}] = inp[{e * lw_in + w - 1}:{e * lw_in}];')
    ports = '.clk(clk), ' if clocked else ''
    lines.append(f'    {inner} core ({ports}.inp(p_in), .out(p_out));')
    for e, (off, w, sg, _f) in enumerate(out_map.elems):
        hi, lo = (e + 1) * lw_out - 1, e * lw_out
        if w == 0:
            lines.append(f"    assign out[{hi}:{lo}] = {lw_out}'d0;")
        elif w == lw_out:
            lines.append(f'    assign out[{hi}:{lo}] = p_out[{off + w - 1}:{off}];')
        else:
            ext = f'{{{lw_out - w}{{p_out[{off + w - 1}]}}}}' if sg else f"{{{lw_out - w}{{1'b0}}}}"
            lines.append(f'    assign out[{hi}:{lo}] = {{{ext}, p_out[{off + w - 1}:{off}]}};')
    lines.append('endmodule')
    return '\n'.join(lines) + '\n', in_map, out_map
