// Helpers shared by generated Verilator binders: bit-field access on
// Verilator port types (plain integers for <=64-bit ports, WData word arrays
// for wider ones) and the OpenMP batch-inference driver.
//
// Parity target: reference src/da4ml/codegen/rtl/common_source/
// {binder_util.hh,ioutil.hh} (bitpack/bitunpack + batch_inference).
#pragma once

#include <cstdint>
#include <type_traits>

#include <verilated.h>

namespace da4ml_binder {

// ---- integral ports (CData/SData/IData/QData) ----
template <typename T, typename std::enable_if<std::is_integral<T>::value, int>::type = 0>
inline void set_bits(T& port, int off, int width, uint64_t val) {
    uint64_t mask = width >= 64 ? ~0ull : ((1ull << width) - 1);
    uint64_t cur = static_cast<uint64_t>(port);
    cur &= ~(mask << off);
    cur |= (val & mask) << off;
    port = static_cast<T>(cur);
}

template <typename T, typename std::enable_if<std::is_integral<T>::value, int>::type = 0>
inline uint64_t get_bits(const T& port, int off, int width) {
    uint64_t mask = width >= 64 ? ~0ull : ((1ull << width) - 1);
    return (static_cast<uint64_t>(port) >> off) & mask;
}

// ---- wide ports (VlWide / WData[N]) ----
template <typename T, typename std::enable_if<!std::is_integral<T>::value, int>::type = 0>
inline void set_bits(T& port, int off, int width, uint64_t val) {
    for (int b = 0; b < width; ++b) {
        int pos = off + b;
        uint32_t bit = (val >> b) & 1;
        port[pos / 32] = (port[pos / 32] & ~(1u << (pos % 32))) | (bit << (pos % 32));
    }
}

template <typename T, typename std::enable_if<!std::is_integral<T>::value, int>::type = 0>
inline uint64_t get_bits(const T& port, int off, int width) {
    uint64_t out = 0;
    for (int b = 0; b < width; ++b) {
        int pos = off + b;
        out |= uint64_t((port[pos / 32] >> (pos % 32)) & 1) << b;
    }
    return out;
}

// Sign-extend a width-bit field to int64.
inline int64_t sext(uint64_t v, int width, bool is_signed) {
    if (!is_signed || width >= 64) return int64_t(v);
    uint64_t sign = 1ull << (width - 1);
    return int64_t((v ^ sign) - sign);
}

}  // namespace da4ml_binder
