// Self-contained integer fixed-point helpers for generated HLS kernels.
//
// The generated kernel is a straight-line DAIS program over int64 codes;
// these helpers give it exact two's-complement wrap / arithmetic-shift
// semantics both in host emulation (g++) and under HLS synthesis (the
// expressions reduce to wires and adders; width recovery is left to the
// scheduler). No vendor ap_fixed/ac_fixed dependency.
//
// Semantics parity: da4ml_tpu/native/src/dais_common.hh and
// da4ml_tpu/runtime/numpy_backend.py.
#pragma once

#include <cstdint>

namespace da {

inline int64_t shl(int64_t v, int s) {
    if (s >= 0) return s >= 64 ? 0 : int64_t(uint64_t(v) << s);
    s = -s;
    if (s >= 64) return v < 0 ? -1 : 0;
    return v >> s;
}

inline int64_t wrap(int64_t v, bool is_signed, int width) {
    if (width <= 0) return 0;
    if (width >= 64) return v;
    const uint64_t mask = (uint64_t(1) << width) - 1;
    uint64_t u = uint64_t(v) & mask;
    if (is_signed && ((u >> (width - 1)) & 1)) u |= ~mask;
    return int64_t(u);
}

inline int64_t requant(int64_t v, int f_from, bool sg, int width, int f_to) {
    return wrap(shl(v, f_to - f_from), sg, width);
}

inline int64_t relu_q(int64_t v, int f_from, bool sg, int width, int f_to) {
    return v < 0 ? 0 : requant(v, f_from, sg, width, f_to);
}

inline bool msb(int64_t v, bool is_signed, int width) {
    if (is_signed) return v < 0;
    if (width <= 0) return false;
    if (width >= 64) return v < 0;
    return v >= (int64_t(1) << (width - 1));
}

inline int64_t shift_add(int64_t a, int64_t b, bool sub, int actual_shift, int gshift) {
    int64_t v2 = sub ? -b : b;
    int64_t s = actual_shift > 0 ? a + shl(v2, actual_shift) : shl(a, -actual_shift) + v2;
    return gshift > 0 ? (s >> gshift) : s;
}

}  // namespace da
