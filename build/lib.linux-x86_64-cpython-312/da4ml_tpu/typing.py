"""Typing re-exports (parity: reference src/da4ml/typing/__init__.py:1-3)."""

from .cmvm import solver_options_t
from .ir import CombLogic, Op, Pipeline, Precision, QInterval
from .trace import HWConfig

__all__ = ['solver_options_t', 'HWConfig', 'CombLogic', 'Pipeline', 'Op', 'QInterval', 'Precision']
