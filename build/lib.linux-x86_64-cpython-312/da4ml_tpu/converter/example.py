"""Built-in example model + plugin — the template third parties follow.

Mirrors the behavior of the reference example (reference
src/da4ml/converter/example.py): a small numpy-defined model exercising
quantize / relu / slicing / a sin lookup table / matmul / einsum, plus the
plugin that traces it. The same ``operation`` runs both eagerly on numpy
arrays (the golden path) and symbolically on FixedVariableArrays.
"""

from __future__ import annotations

import numpy as np

from ..trace import FixedVariableArray
from ..trace.ops import einsum, quantize, relu
from .plugin import TracerPluginBase


def operation(inp):
    """Example computation, traceable and numpy-executable alike."""
    w = np.arange(-60, 60).reshape(4, 5, 6).astype(np.float64) / 2**7
    inp = quantize(inp, 1, 7, 0)  # inputs must be quantized before use
    out1 = relu(inp)

    out2 = inp[:, 1:3].transpose()
    out2 = quantize(np.sin(out2), 1, 0, 7, 'SAT', 'RND')
    out2 = np.repeat(out2, 2, axis=0) * 3 + 4
    out2 = np.amax(np.stack([out2, -out2 * 2], axis=0), axis=0)

    out3 = quantize(out2 @ out1, 1, 10, 2)
    out = einsum('ijk,ij->ik', w, out3)  # CMVM-optimized contraction
    return out


class ExampleModel:
    """Tiny callable model for showcasing the plugin system."""

    def __init__(self, input_shape: tuple[int, ...] | None = None):
        self.input_shape = input_shape

    def __call__(self, x):
        return operation(x)


class ExampleTracer(TracerPluginBase):
    """Plugin for :class:`ExampleModel`.

    Registered under the framework name ``da4ml_tpu`` (the root module of
    ``ExampleModel``) — both in-process and as a ``da4ml_tpu.plugins`` entry
    point in pyproject.toml.
    """

    model: ExampleModel

    def get_input_shapes(self):
        return [self.model.input_shape] if self.model.input_shape is not None else None

    def apply_model(
        self,
        verbose: bool,
        inputs: tuple[FixedVariableArray, ...],
    ) -> tuple[dict[str, FixedVariableArray], list[str]]:
        assert len(inputs) == 1, 'ExampleModel expects a single input.'
        out = operation(inputs[0])
        return {'output': out}, ['output']
