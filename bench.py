"""Benchmark: CMVM DA-search throughput, JAX/TPU backend vs host baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config (BASELINE.md config 1/3): random 16x16 4-bit kernels, batch solve on
the TPU backend vs the best available host backend (native C++ solver when
built, else the sequential Python reference). Acceptance: every JAX solution
is exact (Pipeline.kernel == kernel) and total cost <= host's.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _gen_kernels(n, dim=16, bits=4, seed=0):
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(0, 2**bits, (dim, dim)) * rng.choice([-1.0, 1.0], (dim, dim))).astype(np.float64) for _ in range(n)
    ]


def main():
    from da4ml_tpu.cmvm import solve
    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    kernels = _gen_kernels(n)

    # host baseline: native C++ solver if built, else sequential Python reference
    try:
        from da4ml_tpu.native import has_solver

        host_backend = 'cpp' if has_solver() else 'cpu'
    except Exception:
        host_backend = 'cpu'

    t0 = time.time()
    host_sols = [solve(k, backend=host_backend) for k in kernels]
    host_time = time.time() - t0
    host_rate = n / host_time

    solve_jax_many(kernels)  # warm compile at the timed batch shape
    t0 = time.time()
    jax_sols = solve_jax_many(kernels)
    jax_time = time.time() - t0
    jax_rate = n / jax_time

    n_exact = sum(int(np.array_equal(np.asarray(s.kernel, np.float64), k)) for k, s in zip(kernels, jax_sols))
    host_cost = float(np.mean([s.cost for s in host_sols]))
    jax_cost = float(np.mean([s.cost for s in jax_sols]))

    print(
        json.dumps(
            {
                'metric': 'cmvm_solve_throughput_16x16_int4',
                'value': round(jax_rate, 3),
                'unit': 'matrices/s/chip',
                'vs_baseline': round(jax_rate / host_rate, 3),
                'detail': {
                    'host_backend': host_backend,
                    'host_rate': round(host_rate, 3),
                    'batch': n,
                    'exact': f'{n_exact}/{n}',
                    'mean_cost_jax': jax_cost,
                    'mean_cost_host': host_cost,
                },
            }
        )
    )


if __name__ == '__main__':
    main()
