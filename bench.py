"""Benchmark: CMVM DA-search throughput, JAX/TPU backend vs 16-thread host baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Headline (BASELINE.md config 1): batch-solve random 16x16 int4 kernels on the
JAX backend vs the native C++/OpenMP solver pinned to 16 threads (the
BASELINE.json baseline). detail[] adds config 2 (JEDI-linear MLP layer
kernels), config 3 (dim x bits random sweep), config 4 (QConv2D 3x3 kernels
as im2col constant blocks [kh*kw*Cin, Cout]), and config 5 (a full MLP+Conv
model traced end to end, jax vs cpp solver backend), plus the
compile-vs-search time split of the JAX path. Config entries also record
the device-resident ladder evidence (``fetch_bytes`` / ``upload_bytes`` /
``resident_rungs``); ``--no-device-resident`` runs the legacy host-state
rung loop for A/B captures (docs/benchmarks.md#device-resident-ladder-protocol).

Robustness: the axon TPU plugin can *hang* (not just fail) at backend init,
so the TPU is probed in a bounded subprocess with retries; on failure the
bench runs the device path on CPU XLA and records the probe error in the
JSON line instead of crashing (round-1 failure mode: BENCH_r01 rc=1).
``--resume`` (alias ``--resume-check``) runs the checkpointed-resume drill:
a 3-kernel campaign is started, hard-killed after its first durable
checkpoint record, resumed, and compared bit-for-bit against an
uninterrupted run (docs/reliability.md).

Acceptance per matrix (BASELINE.md): Pipeline.kernel == kernel exactly and
total cost <= host's.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

HOST_THREADS = 16  # BASELINE.json: 16-thread OpenMP baseline

_PROBE_SRC = "import jax; d = jax.devices(); print('PLATFORM=' + d[0].platform)"


def probe_tpu(attempts: int = 2, timeout: float = 90.0):
    """Bounded-subprocess TPU probe with backoff. Returns (platform|None, err).

    The probe inherits the parent environment unchanged, so the platform it
    reports is the one the timed run below will actually initialize.
    ``DA4ML_BENCH_PLATFORM=cpu`` skips probing entirely (explicit override).
    A probe *timeout* (wedged tunnel, can stay down for hours) is not
    retried — only fast init errors are, matching the round-1 failure mode.
    """
    if os.environ.get('DA4ML_BENCH_PLATFORM') == 'cpu':
        return None, 'platform forced to cpu (DA4ML_BENCH_PLATFORM)'
    err = None
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, '-c', _PROBE_SRC],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
            lines = r.stdout.strip().splitlines()
            if r.returncode == 0 and lines and lines[-1].startswith('PLATFORM='):
                return lines[-1].split('=', 1)[1], None
            tail = (r.stderr or '').strip().splitlines()
            err = (tail[-1] if tail else f'probe rc={r.returncode}')[:300]
        except subprocess.TimeoutExpired:
            return None, f'TPU init probe timed out after {timeout:.0f}s (wedged tunnel; not retried)'
        if i + 1 < attempts:
            time.sleep(10.0 * (i + 1))
    return None, err


def _rand_kernel(rng, n_in, n_out, bits):
    mag = rng.integers(0, 2**bits, (n_in, n_out)).astype(np.float64)
    return mag * rng.choice([-1.0, 1.0], (n_in, n_out))


def _host_solve(kernels, backend):
    """Host baseline solve, threaded as wide as this machine allows.

    Requesting more OpenMP workers than cores only adds scheduler noise, so
    the measured run uses min(16, nproc) workers; ``_host_16t_rate`` derives
    the BASELINE 16-thread figure from it by assuming perfect scaling of the
    missing cores (an upper bound on the real 16-thread host — the dc sweep
    has too few lanes to scale perfectly).
    """
    from da4ml_tpu.cmvm import solve

    workers = min(HOST_THREADS, os.cpu_count() or 1)
    t0 = time.perf_counter()
    sols = [solve(k, backend=backend, n_workers=workers) for k in kernels]
    return sols, time.perf_counter() - t0


def _host_16t_rate(n: int, host_t: float) -> float:
    """Derived perfect-scaling 16-thread host rate (matrices/s).

    Clamped by the matrix count: perfect scaling can only be assumed over
    independent work, and with n matrices there are at most n independent
    solves — deriving a flat 16x/workers factor from n < 16 matrices
    overstated the baseline for small configs (e.g. 2_jedi_mlp_layers).
    """
    workers = min(HOST_THREADS, os.cpu_count() or 1)
    eff = min(HOST_THREADS, max(workers, n))
    return n / host_t * (eff / workers)


def _jax_solve(kernels):
    """(solutions, steady_time, compile_time): first call pays XLA compiles."""
    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    t0 = time.perf_counter()
    solve_jax_many(kernels)
    first = time.perf_counter() - t0
    t0 = time.perf_counter()
    sols = solve_jax_many(kernels)
    steady = time.perf_counter() - t0
    return sols, steady, max(first - steady, 0.0)


def _parity(kernels, jax_sols, host_sols):
    n_exact = sum(int(np.array_equal(np.asarray(s.kernel, np.float64), k)) for k, s in zip(kernels, jax_sols))
    return {
        'exact': f'{n_exact}/{len(kernels)}',
        'mean_cost_jax': round(float(np.mean([s.cost for s in jax_sols])), 3),
        'mean_cost_host': round(float(np.mean([s.cost for s in host_sols])), 3),
    }


def _run_config(name, kernels, host_backend):
    from da4ml_tpu.telemetry.metrics import metrics_snapshot

    host_sols, host_t = _host_solve(kernels, host_backend)
    pre = metrics_snapshot()
    jax_sols, jax_t, compile_t = _jax_solve(kernels)
    post = metrics_snapshot()

    def _delta(metric: str) -> int:
        return int(post.get(metric, {}).get('value', 0) - pre.get(metric, {}).get('value', 0))

    n = len(kernels)
    entry = {
        'config': name,
        'n_matrices': n,
        'host_rate': round(n / host_t, 3),
        # the BASELINE comparison point: measured host rate scaled to 16
        # perfect threads (methodology: docs/benchmarks.md)
        'host_rate_16thread_derived': round(_host_16t_rate(n, host_t), 3),
        'jax_rate': round(n / jax_t, 3),
        'speedup': round(host_t / jax_t, 3),
        'speedup_vs_16thread': round((n / jax_t) / _host_16t_rate(n, host_t), 3),
        'jax_compile_s': round(compile_t, 2),
        # device-resident ladder evidence (docs/benchmarks.md#device-resident):
        # host<->device traffic and on-device rung transitions across both
        # jax solves; A/B against `--no-device-resident` to see the drop
        'fetch_bytes': _delta('sched.fetch_bytes'),
        'upload_bytes': _delta('sched.upload_bytes'),
        'resident_rungs': _delta('sched.device_resident_rungs'),
        **_parity(kernels, jax_sols, host_sols),
    }
    return entry


def _trace_model(backend: str, limited: bool):
    """Trace the config-5 model (BASELINE.md: MLP+Conv, all layers CMVM)."""
    import da4ml_tpu.trace.ops.conv_utils as cu
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    rng = np.random.default_rng(5)
    side, cin, cmid, dense = (4, 2, 4, 8) if limited else (8, 3, 8, 32)
    flat = (side // 2) ** 2 * cmid  # after 'same' conv + 2x2 max-pool
    w1 = rng.integers(-32, 32, (3, 3, cin, cmid)).astype(np.float64)
    w2 = rng.integers(-32, 32, (flat, dense)).astype(np.float64)
    w3 = rng.integers(-32, 32, (dense, 5)).astype(np.float64)
    if backend == 'jax':
        # what the keras/torch converter front-ends do automatically: compile
        # every layer's shape classes in the background while earlier layers
        # solve (model-level prewarm; no-op where prewarm is disabled)
        from da4ml_tpu.cmvm import prewarm_for_kernels

        prewarm_for_kernels([[w1.reshape(-1, cmid)], [w2], [w3]], adder_size=1, carry_size=-1)
    inp = FixedVariableArrayInput((side, side, cin), hwconf=HWConfig(1, -1, -1), solver_options={'backend': backend})
    x = inp.quantize(np.ones((side, side, cin)), np.full((side, side, cin), 3), np.full((side, side, cin), 2))
    x = cu.conv2d(x, w1, padding='same')
    x = x.relu(i=np.full(x.shape, 6), f=np.full(x.shape, 2))
    x = cu.max_pool2d(x, 2)
    x = x.reshape(-1)
    x = (x @ w2).relu(i=np.full(dense, 7), f=np.full(dense, 2))
    return comb_trace(inp, x @ w3)


def _run_model_config(limited: bool, host_backend: str = 'cpp'):
    """Config 5: end-to-end model build time (trace + every CMVM solve).

    Reported twice: cold (first trace pays every XLA compile not already in
    the persistent cache) and warm (second trace, compile-amortized — the
    steady state for a conversion sweep or any reuse of the cache). The
    headline ``speedup`` is the warm one; ``speedup_cold`` is the honest
    first-ever-run number.
    """
    t0 = time.perf_counter()
    comb_host = _trace_model(host_backend, limited)
    host_t = time.perf_counter() - t0
    t0 = time.perf_counter()
    comb_jax = _trace_model('jax', limited)
    jax_cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    _trace_model('jax', limited)
    jax_warm = time.perf_counter() - t0
    return {
        'config': '5_full_model_trace',
        'host_s': round(host_t, 3),
        'jax_cold_s': round(jax_cold, 3),
        'jax_s': round(jax_warm, 3),
        'speedup': round(host_t / jax_warm, 3),
        'speedup_cold': round(host_t / jax_cold, 3),
        'cost_jax': float(comb_jax.cost),
        'cost_host': float(comb_host.cost),
    }


def _run_inference_micro(limited: bool):
    """DAIS inference samples/s: jitted device kernel vs native interpreter."""
    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.runtime.jax_backend import DaisExecutor
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    rng = np.random.default_rng(11)
    n_in, hidden = (8, 16) if limited else (16, 64)
    inp = FixedVariableArrayInput(n_in, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(n_in), np.full(n_in, 3), np.full(n_in, 2))
    w1 = rng.integers(-8, 8, (n_in, hidden)).astype(np.float64)
    x = (x @ w1).relu(i=np.full(hidden, 6), f=np.full(hidden, 2))
    w2 = rng.integers(-8, 8, (hidden, 8)).astype(np.float64)
    comb = comb_trace(inp, x @ w2)

    n_samples = 4096 if limited else 262144
    data = rng.uniform(-8, 8, (n_samples, n_in))

    ex = DaisExecutor(decode(comb.to_binary()))
    out_dev = ex(data)  # first call pays the compile
    t0 = time.perf_counter()
    out_dev = ex(data)
    dev_t = time.perf_counter() - t0

    # device-resident rate: input already on device, output not fetched —
    # the steady state when inference feeds another device computation (the
    # end-to-end rate above is dominated by tunnel transfers on this setup)
    import jax

    x_dev = jax.device_put(ex._int_inputs(data))
    jax.block_until_ready(ex.fn_int(x_dev))
    t0 = time.perf_counter()
    jax.block_until_ready(ex.fn_int(x_dev))
    res_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    out_host = comb.predict(data, n_threads=HOST_THREADS)
    host_t = time.perf_counter() - t0

    # per-mode regression surface: rate + compile seconds for each concrete
    # execution mode (docs/runtime.md) on a capped batch (scan's execution
    # buffer is n_ops x batch; the headline device_rate above stays full-size)
    prog = decode(comb.to_binary())
    mode_n = min(n_samples, 65536)
    mode_data = data[:mode_n]
    host_ref = out_host[:mode_n]
    modes = {}
    for m in ('unroll', 'scan', 'level', 'pallas'):
        try:
            t0 = time.perf_counter()
            exm = DaisExecutor(prog, mode=m)
            if m == 'pallas' and exm.mode != 'pallas':
                modes[m] = {'skipped': 'pallas unavailable (fell back to level)'}
                continue
            out_m = exm(mode_data)  # first call pays the compile
            compile_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            out_m = exm(mode_data)
            mt = time.perf_counter() - t0
            modes[m] = {
                'rate': round(mode_n / mt, 1),
                'compile_s': round(compile_s, 3),
                'bit_exact': bool(np.array_equal(out_m, host_ref)),
            }
        except Exception as e:
            modes[m] = {'error': f'{type(e).__name__}: {e}'[:160]}

    # >UNROLL_LIMIT program (ir.synth, layered): unroll must refuse, level
    # must compile in O(depth x families) and outrun the scan interpreter
    large = _run_large_program_probe(limited)

    # multi-stage pipeline: fused single-program vs per-stage chained jax
    from da4ml_tpu.trace import to_pipeline

    pipe = to_pipeline(comb, 3.0)
    out_f = pipe.predict(data, backend='jax')  # compiles
    t0 = time.perf_counter()
    out_f = pipe.predict(data, backend='jax')
    fused_t = time.perf_counter() - t0
    chain = [s.to_binary() for s in pipe.stages]

    # chained = per-stage jitted programs with device-resident donated
    # intermediates (run_pipeline(fused=False)); hostloop = the legacy
    # float host round-trip at every stage boundary
    from da4ml_tpu.runtime.jax_backend import run_binary, run_pipeline

    run_pipeline(chain, data, fused=False)
    t0 = time.perf_counter()
    out_c = run_pipeline(chain, data, fused=False)
    chain_t = time.perf_counter() - t0

    def _hostloop(d):
        out = d
        for b in chain:
            out = run_binary(b, out)
        return out

    _hostloop(data)
    t0 = time.perf_counter()
    out_h = _hostloop(data)
    hostloop_t = time.perf_counter() - t0

    # fused-IR: the stages merged into ONE level-packed DAIS program
    # (docs/runtime.md#ir-fusion) — no boundary pack/shift/unpack at all
    run_pipeline(chain, data, fused='ir')
    t0 = time.perf_counter()
    out_ir = run_pipeline(chain, data, fused='ir')
    fused_ir_t = time.perf_counter() - t0
    return {
        'n_samples': n_samples,
        'device_rate': round(n_samples / dev_t, 1),
        'device_resident_rate': round(n_samples / res_t, 1),
        'host_rate': round(n_samples / host_t, 1),
        'speedup': round(host_t / dev_t, 3),
        'speedup_resident': round(host_t / res_t, 3),
        'bit_exact': bool(np.array_equal(out_dev, out_host)),
        'auto_mode': ex.mode,
        'modes': modes,
        'large_program': large,
        'pipeline_stages': len(pipe.stages),
        'pipeline_fused_rate': round(n_samples / fused_t, 1),
        'pipeline_fused_ir_rate': round(n_samples / fused_ir_t, 1),
        'pipeline_chained_rate': round(n_samples / chain_t, 1),
        'pipeline_hostloop_rate': round(n_samples / hostloop_t, 1),
        'pipeline_fused_vs_chained': round(chain_t / fused_t, 3),
        'pipeline_fused_ir_vs_chained': round(chain_t / fused_ir_t, 3),
        'pipeline_bit_exact': bool(
            np.array_equal(out_f, out_host) and np.array_equal(out_c, out_host) and np.array_equal(out_h, out_host)
        ),
        'pipeline_fused_ir_bit_exact': bool(np.array_equal(out_ir, out_host)),
        'model_shard': _run_model_shard_probe([comb.to_binary()], mode_data, host_ref),
        'fusion_workloads': _run_fusion_workloads(limited),
    }


def _run_model_shard_probe(chain, data, golden) -> dict:
    """Model-axis partition vs single-device on the fused program
    (docs/runtime.md#model-parallel-execution). The rate comparison only
    means much on a real multi-chip mesh — on a virtual CPU mesh the gate
    is bit-exactness, mirroring the autotuner's own contract (sharded is
    only ever *picked* when it wins the measured race)."""
    import jax

    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.ir.fuse import fuse_binaries
    from da4ml_tpu.ir.partition import partition_program
    from da4ml_tpu.parallel import model_mesh
    from da4ml_tpu.runtime.jax_backend import DaisExecutor

    n_dev = jax.local_device_count()
    k = 4 if n_dev % 4 == 0 else n_dev
    if model_mesh(k) is None:
        return {'skipped': f'no {k}-way model mesh ({n_dev} local devices)'}
    prog = decode(fuse_binaries(chain) if len(chain) > 1 else chain[0])
    plan = partition_program(prog, k)
    single = DaisExecutor(prog, model_shard=False)
    sharded = DaisExecutor(prog, partition_plan=plan, model_shard=True)
    if sharded.model_shards != k:
        return {'skipped': 'sharded build fell back to single-device'}
    timed = {}
    outs = {}
    for key, ex in (('sharded', sharded), ('single', single)):
        ex(data)  # first call pays the compile
        t0 = time.perf_counter()
        outs[key] = ex(data)
        timed[key] = time.perf_counter() - t0
    build = sharded._shard_build
    itemsize = 8 if sharded.use_i64 else 4
    n = len(data)
    return {
        'k': k,
        'segments': plan.n_segments,
        'sharded_rate': round(n / timed['sharded'], 1),
        'single_rate': round(n / timed['single'], 1),
        'vs_single_device': round(timed['single'] / timed['sharded'], 3),
        'exchange_bytes': int(sum(build.exchange_rows(g) for g in range(build.n_segments)) * itemsize),
        'imbalance': round(build.imbalance, 3),
        'bit_exact': bool(np.array_equal(outs['sharded'], golden) and np.array_equal(outs['single'], golden)),
    }


def _run_fusion_workloads(limited: bool) -> dict:
    """ROADMAP workload coverage for the fusion pass: a depthwise+pointwise
    separable conv stack and a softmax-free (relu-attention) transformer
    block, each traced with the existing tracer ops, split into a pipeline
    and run fused-IR vs chained vs per-stage hostloop (bit-exact gated)."""
    from da4ml_tpu.ir.fuse import fuse_pipeline
    from da4ml_tpu.runtime.jax_backend import run_binary, run_pipeline
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline
    from da4ml_tpu.trace.ops import conv2d, depthwise_conv2d, einsum, relu
    from da4ml_tpu.trace.ops.quantization import quantize

    rng = np.random.default_rng(23)
    n_samples = 8192 if limited else 65536

    def conv_stack():
        # same separable stack as tests/test_fuse.py so the stage split is known-good
        shape = (5, 5, 2)
        inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, 6))
        x = inp.quantize(np.ones(shape), np.full(shape, 2), np.zeros(shape, np.int64))
        h = relu(depthwise_conv2d(x, rng.integers(-3, 4, (3, 3, 2, 1)).astype(np.float64)), i=3, f=0)
        h = relu(conv2d(h, rng.integers(-3, 4, (1, 1, 2, 3)).astype(np.float64)), i=3, f=0)
        h = relu(depthwise_conv2d(h, rng.integers(-2, 3, (2, 2, 3, 1)).astype(np.float64)), i=3, f=0)
        out = conv2d(h, rng.integers(-3, 4, (1, 1, 3, 2)).astype(np.float64))
        return to_pipeline(comb_trace(inp, out), 6, retiming=False), int(np.prod(shape))

    def transformer_block():
        T, D, F = (4, 4, 8) if limited else (8, 8, 16)
        shape = (T, D)
        inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, 8))
        x = inp.quantize(np.ones(shape), np.full(shape, 2), np.zeros(shape, np.int64))
        wq, wk, wv = (rng.integers(-2, 3, (D, D)).astype(np.float64) for _ in range(3))
        q = quantize(einsum('td,df->tf', x, wq), 1, 3, 0)
        k = quantize(einsum('td,df->tf', x, wk), 1, 3, 0)
        v = quantize(einsum('td,df->tf', x, wv), 1, 3, 0)
        scores = relu(einsum('td,sd->ts', q, k), i=3, f=0)  # relu-attention, no softmax
        h = quantize(x + quantize(einsum('ts,sd->td', scores, v), 1, 3, 0), 1, 3, 0)
        w1 = rng.integers(-2, 3, (D, F)).astype(np.float64)
        w2 = rng.integers(-2, 3, (F, D)).astype(np.float64)
        ffn = quantize(einsum('tf,fd->td', relu(einsum('td,df->tf', h, w1), i=3, f=0), w2), 1, 3, 0)
        return to_pipeline(comb_trace(inp, quantize(h + ffn, 1, 3, 0)), 8, retiming=False), T * D

    entries = {}
    for wname, build in (('conv_stack', conv_stack), ('transformer_block', transformer_block)):
        pipe, n_in = build()
        chain = [s.to_binary() for s in pipe.stages]
        data = rng.integers(-4, 4, (n_samples, n_in)).astype(np.float64)
        golden = pipe.predict(data, backend='numpy')
        _, rep = fuse_pipeline(pipe, report=True)

        def hostloop(d):
            out = d
            for b in chain:
                out = run_binary(b, out)
            return out

        timed = {}
        outs = {}
        for key, fn in (
            ('fused_ir', lambda: run_pipeline(chain, data, fused='ir')),
            ('chained', lambda: run_pipeline(chain, data, fused=False)),
            ('hostloop', lambda: hostloop(data)),
        ):
            fn()  # first call pays the compile
            t0 = time.perf_counter()
            outs[key] = fn()
            timed[key] = time.perf_counter() - t0

        # pallas column: the same IR-fused program through ONE mega-kernel
        # (interpret mode on CPU runners — the rate only means much on an
        # accelerator, but bit_exact is gated everywhere)
        from da4ml_tpu.runtime.jax_backend import fused_executor_for_binaries

        pallas_entry = None
        try:
            ex_p = fused_executor_for_binaries(chain, mode='pallas')
            if ex_p.mode == 'pallas':
                ex_p(data)  # first call pays the compile
                t0 = time.perf_counter()
                outs['pallas'] = ex_p(data)
                timed['pallas'] = time.perf_counter() - t0
                pallas_entry = {
                    'pallas_rate': round(n_samples / timed['pallas'], 1),
                    'pallas_vs_level': round(timed['fused_ir'] / timed['pallas'], 3),
                    'pallas_bit_exact': bool(np.array_equal(outs['pallas'], golden)),
                }
            else:
                pallas_entry = {'pallas_skipped': 'pallas unavailable (fell back to level)'}
        except Exception as e:
            pallas_entry = {'pallas_error': f'{type(e).__name__}: {e}'[:160]}
        entries[wname] = {
            'stages': len(pipe.stages),
            'n_in': n_in,
            'n_samples': n_samples,
            'seam_ops': rep.seam_ops,
            'depth_chained': rep.depth_before,
            'depth_fused': rep.depth_after,
            'fused_ir_rate': round(n_samples / timed['fused_ir'], 1),
            'chained_rate': round(n_samples / timed['chained'], 1),
            'hostloop_rate': round(n_samples / timed['hostloop'], 1),
            'fused_ir_vs_chained': round(timed['chained'] / timed['fused_ir'], 3),
            'bit_exact': bool(all(np.array_equal(outs[k], golden) for k in outs)),
            'model_shard': _run_model_shard_probe(chain, data, golden),
            **(pallas_entry or {}),
        }
    return entries


def _run_large_program_probe(limited: bool) -> dict:
    """level-mode acceptance probe: a layered >20k-op DAIS program that
    ``unroll`` refuses must compile under ``level`` and outrun ``scan``."""
    from da4ml_tpu.ir.synth import random_inputs, random_program
    from da4ml_tpu.runtime.jax_backend import DaisExecutor
    from da4ml_tpu.runtime.numpy_backend import run_program

    rng = np.random.default_rng(17)
    big = random_program(rng, n_ops=21_000, n_in=16, n_out=8, n_levels=24)
    bdata = random_inputs(rng, big, 128 if limited else 4096)
    entry: dict = {'n_ops': big.n_ops, 'n_samples': len(bdata)}
    try:
        DaisExecutor(big, mode='unroll')
        entry['unroll_refused'] = False
    except ValueError:
        entry['unroll_refused'] = True
    ref = run_program(big, bdata)
    for m in ('level', 'scan'):
        try:
            t0 = time.perf_counter()
            exm = DaisExecutor(big, mode=m)
            out = exm(bdata)
            entry[f'{m}_compile_s'] = round(time.perf_counter() - t0, 3)
            t0 = time.perf_counter()
            out = exm(bdata)
            dt = time.perf_counter() - t0
            entry[f'{m}_rate'] = round(len(bdata) / dt, 1)
            entry[f'{m}_bit_exact'] = bool(np.array_equal(out, ref))
        except Exception as e:
            entry[f'{m}_error'] = f'{type(e).__name__}: {e}'[:160]
    if entry.get('level_rate') and entry.get('scan_rate'):
        entry['level_vs_scan'] = round(entry['level_rate'] / entry['scan_rate'], 3)
    return entry


def _section_kernels(name: str, n1: int, limited: bool):
    """Deterministic per-section kernel sets (independent rng streams)."""
    rng = np.random.default_rng(20260729)
    if name == '1_16x16_int4':
        return [_rand_kernel(rng, 16, 16, 4) for _ in range(min(n1, 16) if limited else n1)]
    if name == '2_jedi_mlp_layers':
        shapes = ((16, 64), (64, 32), (32, 32), (32, 5))
        if limited:
            shapes = tuple((ni, no) for ni, no in shapes if max(ni, no) <= 32)
        return [_rand_kernel(rng, ni, no, 6) for ni, no in shapes]
    if name == '3_dim_bits_sweep':
        shapes = ((8, 2), (8, 8), (16, 4), (32, 4), (32, 8), (64, 2), (64, 6))
        if limited:
            shapes = tuple((d, b) for d, b in shapes if d <= 16)
        return [_rand_kernel(rng, d, d, b) for d, b in shapes]
    if name == '3b_large_dim':
        # the BASELINE sweep's large end (its span is 8-256 dim): a 128-dim
        # instance searches fully on device; a 256-dim instance (opt-in,
        # DA4ML_BENCH_LARGE=1) keeps its decomposed dc lanes on device while
        # the undecomposed lane exceeds single-chip memory and runs host-side
        # via lane-level routing
        shapes = [(24, 4)] if limited else [(128, 6)]
        if os.environ.get('DA4ML_BENCH_LARGE') == '1' and not limited:
            shapes.append((256, 4))
        return [_rand_kernel(rng, d, d, b) for d, b in shapes]
    if name == '4_qconv3x3_im2col':
        shapes = ((1, 8), (4, 8), (8, 16), (16, 16))
        if limited:
            shapes = tuple((ci, co) for ci, co in shapes if 9 * ci <= 36)
        return [_rand_kernel(rng, 9 * ci, co, 6) for ci, co in shapes]
    raise ValueError(f'unknown kernel section {name!r}')


def _resolve_host_backend() -> str:
    try:
        from da4ml_tpu.native import has_solver

        return 'cpp' if has_solver() else 'cpu'
    except Exception:
        return 'cpu'


def run_section(name: str, n1: int, limited: bool) -> dict:
    """Run one bench section in this process and return its result dict.

    Called in a child subprocess (``--section``) so a device hang or worker
    crash in one section cannot take down the whole bench (round-1 failure
    mode: a wedged axon tunnel blocks forever, not errors).

    The telemetry metrics registry is armed for the section, and its
    snapshot (jit compile/execute splits, CSE round counters, solve
    histograms — docs/telemetry.md) rides along in the section entry under
    ``'metrics'``.
    """
    from da4ml_tpu.telemetry.metrics import enable_metrics, metrics_snapshot

    enable_metrics()
    entry = _run_section_impl(name, n1, limited)
    if isinstance(entry, dict):
        snap = metrics_snapshot()
        if snap:
            entry.setdefault('metrics', snap)
    return entry


def _run_section_impl(name: str, n1: int, limited: bool) -> dict:
    import jax

    if os.environ.get('DA4ML_BENCH_PLATFORM') == 'cpu':
        jax.config.update('jax_platforms', 'cpu')
    # persistent compile cache: DA4ML_XLA_CACHE (legacy DA4ML_JAX_CACHE)
    # or ~/.cache/da4ml_tpu/xla; --no-persistent-cache sets the env to '0'
    from da4ml_tpu.cmvm.jax_search import ensure_compile_cache

    ensure_compile_cache()
    host_backend = _resolve_host_backend()

    def _with_shape_classes(entry: dict) -> dict:
        # distinct compiled device programs this section needed (canonical
        # shape classes; the persistent XLA cache makes them one-time
        # costs), the executables they expand to ((class, lane bucket)
        # pairs), and the compile-vs-persistent-cache split of first calls
        from da4ml_tpu.cmvm.jax_search import _build_cse_fn, executable_classes
        from da4ml_tpu.telemetry.metrics import metrics_snapshot

        entry['shape_classes'] = _build_cse_fn.cache_info().currsize
        entry['buckets'] = executable_classes()
        snap = metrics_snapshot()
        entry['compile_cache'] = {
            'compile': int(snap.get('jit.compile', {}).get('value', 0)),
            'cache_load': int(snap.get('jit.cache_load', {}).get('value', 0)),
        }
        return entry

    if name == '5_full_model_trace':
        return _with_shape_classes(_run_model_config(limited, host_backend))
    if name == 'dais_inference':
        return _run_inference_micro(limited)
    if name == 'quality_sweep':
        from da4ml_tpu.cmvm.jax_search import solve_jax_many

        k1 = _section_kernels('1_16x16_int4', n1, limited)
        host_sols, _ = _host_solve(k1, host_backend)
        host_costs = np.asarray([s.cost for s in host_sols])
        single = solve_jax_many(k1)
        t0 = time.perf_counter()
        methods = ['wmc', 'mc'] if limited else ['wmc', 'mc', 'wmc-dc']
        wide = solve_jax_many(k1, method0_candidates=methods, n_restarts=2 if limited else 6)
        wall = time.perf_counter() - t0
        wide_costs = np.asarray([s.cost for s in wide])
        portfolio = solve_jax_many(k1, include_host=True)
        portfolio_costs = np.asarray([s.cost for s in portfolio])
        return {
            'mean_cost_wide': round(float(wide_costs.mean()), 3),
            'mean_cost_single': round(float(np.mean([s.cost for s in single])), 3),
            'mean_cost_host': round(float(host_costs.mean()), 3),
            'mean_cost_portfolio': round(float(portfolio_costs.mean()), 3),
            # pure device sweep vs a fresh host solve, per matrix
            'win_or_tie_device_only': f'{int((wide_costs <= host_costs).sum())}/{len(k1)}',
            'strict_win_device_only': f'{int((wide_costs < host_costs).sum())}/{len(k1)}',
            # include_host portfolio (the documented never-worse mode)
            'win_or_tie_portfolio': f'{int((portfolio_costs <= host_costs).sum())}/{len(k1)}',
            'wall_s': round(wall, 2),
        }
    if name == 'quality_beam':
        # the quality= knob's headline numbers (docs/cmvm.md#search-strategies):
        # strict-win rate of the beam-4 portfolio vs the host oracle on the
        # quality-sweep corpus, never-worse accounting, and the wall-clock
        # multiplier vs the greedy device solve — the CI quality-gate's
        # committed-corpus twin (ci/quality_gate.py gates the same invariants)
        from da4ml_tpu.cmvm.jax_search import solve_jax_many
        from da4ml_tpu.telemetry.metrics import metrics_snapshot

        k1 = _section_kernels('1_16x16_int4', n1, limited)
        host_sols, _ = _host_solve(k1, host_backend)
        host_costs = np.asarray([s.cost for s in host_sols])
        solve_jax_many(k1[:2])  # warm the dominant shape classes
        t0 = time.perf_counter()
        greedy = solve_jax_many(k1)
        greedy_wall = time.perf_counter() - t0
        greedy_costs = np.asarray([s.cost for s in greedy])
        pre = metrics_snapshot()
        t0 = time.perf_counter()
        beam = solve_jax_many(k1, quality='search')
        beam_wall = time.perf_counter() - t0
        post = metrics_snapshot()
        beam_costs = np.asarray([s.cost for s in beam])

        def _delta(metric: str) -> int:
            return int(post.get(metric, {}).get('value', 0) - pre.get(metric, {}).get('value', 0))

        return {
            'quality': 'search',
            'n_kernels': len(k1),
            'strict_wins': f'{int((beam_costs < host_costs).sum())}/{len(k1)}',
            'win_or_tie': f'{int((beam_costs <= host_costs).sum())}/{len(k1)}',
            'never_worse_than_greedy': f'{int((beam_costs <= greedy_costs).sum())}/{len(k1)}',
            'mean_cost_host': round(float(host_costs.mean()), 3),
            'mean_cost_greedy': round(float(greedy_costs.mean()), 3),
            'mean_cost_beam': round(float(beam_costs.mean()), 3),
            'greedy_wall_s': round(greedy_wall, 2),
            'beam_wall_s': round(beam_wall, 2),
            'wall_multiplier': round(beam_wall / greedy_wall, 2) if greedy_wall > 0 else None,
            # device-resident beam evidence (docs/benchmarks.md#device-resident):
            # host<->device traffic of the whole quality solve, on-device
            # fork/prune activity, and the entry-carry handoffs — A/B against
            # `--no-device-resident` (host beam + legacy ladder) for the drop
            'fetch_bytes': _delta('sched.fetch_bytes'),
            'upload_bytes': _delta('sched.upload_bytes'),
            'resident_rungs': _delta('sched.device_resident_rungs'),
            'device_forks': _delta('search.device_forks'),
            'device_prunes': _delta('search.device_prunes'),
            'host_seeded_lanes': _delta('search.host_seeded_lanes'),
            'entry_carry_groups': _delta('sched.entry_carry_groups'),
        }
    if name == 'quality_1000':
        # on-demand (not in the default budget): the reference-scale quality
        # sweep — 1000 random kernels, dims 2-32, 1-8 bit, device vs host
        # cost distribution (reference bench.py / wtf.py scale)
        from da4ml_tpu.cmvm.jax_search import solve_jax_many

        rng = np.random.default_rng(1000)
        n = 96 if limited else 1000
        kernels = []
        for _ in range(n):
            d1, d2 = int(rng.integers(2, 33)), int(rng.integers(2, 33))
            kernels.append(_rand_kernel(rng, d1, d2, int(rng.integers(1, 9))))
        host_sols, host_t = _host_solve(kernels, host_backend)
        solve_jax_many(kernels[:8])  # warm the dominant shape classes
        t0 = time.perf_counter()
        jax_sols = solve_jax_many(kernels)
        jt = time.perf_counter() - t0
        hc = np.asarray([s.cost for s in host_sols])
        dc = np.asarray([s.cost for s in jax_sols])
        d = dc - hc
        return {
            'n_kernels': n,
            'identical': int((d == 0).sum()),
            'win': int((d < 0).sum()),
            'loss': int((d > 0).sum()),
            'mean_cost_host': round(float(hc.mean()), 3),
            'mean_cost_jax': round(float(dc.mean()), 3),
            'mean_delta': round(float(d.mean()), 4),
            'max_loss': float(d.max()),
            'max_win': float(-d.min()),
            'host_rate': round(n / host_t, 2),
            'jax_rate': round(n / jt, 2),
        }
    if name == 'campaign':
        # fault-tolerant multi-worker campaign probe (docs/distributed.md):
        # the same small corpus solved single-process (reference) and with
        # 3 worker subprocesses over a shared-filesystem lease queue —
        # scaling efficiency = t1 / (N * tN), byte-identity is the campaign
        # invariant the chaos CI job gates harder
        import tempfile

        from da4ml_tpu.parallel import campaign as _camp

        rng = np.random.default_rng(7000)
        n = 8 if limited else 24
        kernels = [_rand_kernel(rng, int(rng.integers(4, 13)), int(rng.integers(4, 13)), 4) for _ in range(n)]
        workers = 3
        with tempfile.TemporaryDirectory() as td:
            ref_results, ref_rep = _camp.run_campaign(
                kernels, workers=1, campaign_dir=os.path.join(td, 'ref'), backend='native-threads'
            )
            par_results, par_rep = _camp.run_campaign(
                kernels,
                workers=workers,
                campaign_dir=os.path.join(td, 'par'),
                backend='native-threads',
                ttl_s=10.0,
                poll_s=0.2,
            )
        ref_blobs = {d['key']: json.dumps(d['pipeline'], sort_keys=True) for d in ref_results}
        par_blobs = {d['key']: json.dumps(d['pipeline'], sort_keys=True) for d in par_results}
        t1, tn = ref_rep['wall_s'], par_rep['wall_s']
        return {
            'n_kernels': n,
            'workers': workers,
            'single_wall_s': round(t1, 3),
            'campaign_wall_s': round(tn, 3),
            # tn includes ~1s/worker interpreter+import startup, so small
            # corpora under-report; the honest floor, not a headline
            'scaling_efficiency': round(t1 / (workers * tn), 3) if tn > 0 else None,
            'speedup': round(t1 / tn, 3) if tn > 0 else None,
            'kernels_stolen': par_rep['kernels_stolen'],
            'byte_identical': ref_blobs == par_blobs,
            'mean_cost': round(float(np.mean([d['cost'] for d in par_results])), 3),
        }
    if name == 'serve':
        # resilient serving probe (docs/serving.md): closed-loop load over
        # the in-process engine — p50/p99 latency + sustained samples/s,
        # every response bit-exact vs the numpy oracle, and (after the
        # canonical-grid warmup) zero serve batches landing on a new XLA
        # shape; plus the 10x overload burst proving the admission ceiling
        from da4ml_tpu.cmvm import solve as _solve
        from da4ml_tpu.runtime.numpy_backend import run_binary as _np_run
        from da4ml_tpu.serve import ServeConfig, ServeEngine
        from da4ml_tpu.serve.loadgen import burst, closed_loop, engine_infer_fn, make_request_pool
        from da4ml_tpu.telemetry.metrics import metrics_snapshot

        rng = np.random.default_rng(9000)
        pipe = _solve(_rand_kernel(rng, 12, 8, 4), backend=host_backend)
        cfg = ServeConfig(max_batch_rows=64, max_latency_ms=1.0, queue_cap_rows=512, default_deadline_ms=2000.0)
        engine = ServeEngine(cfg)
        engine.load_model('bench', pipe)  # prewarms the canonical batch grid
        bins = engine._state('bench').binaries

        def oracle(x):
            out = np.asarray(x, np.float64)
            for b in bins:
                out = _np_run(b, out)
            return out

        pool = make_request_pool(oracle, engine._state('bench').n_in, rows_choices=(1, 2, 4, 8, 16), pool=40)
        infer = engine_infer_fn(engine, 'bench')
        duration = 2.0 if limited else 6.0
        load = closed_loop(infer, pool, workers=8, duration_s=duration, deadline_ms=2000.0)
        snap = metrics_snapshot()
        shape_miss = int(snap.get('serve.shape_miss', {}).get('value', 0))
        sustainable = max(int((load['samples_per_s'] or 1) * 0.1), 32)
        overload = burst(infer, pool, n_requests=min(10 * max(sustainable, 1), 400), deadline_ms=2000.0)
        drained = engine.close()
        return {
            'p50_ms': load['p50_ms'],
            'p99_ms': load['p99_ms'],
            'samples_per_s': load['samples_per_s'],
            'requests': load['requests'],
            'availability': load['availability'],
            'bit_exact': load['mismatches'] == 0 and overload['mismatches'] == 0,
            'shed': load['shed'],
            'shape_miss_after_warmup': shape_miss,
            'burst_requests': overload['requests'],
            'burst_ok': overload['ok'],
            'burst_shed': overload['shed'],
            'burst_resolved_all': overload['resolved_all'],
            'drained_clean': drained,
        }
    if name == 'store':
        # solution-store probe (docs/store.md): cold-fill a fresh store,
        # replay the corpus warm (hit path = lookup + verify-on-read, must
        # be byte-identical and far under cold-solve latency), then race an
        # in-process herd on one fresh key to prove single-flight dedup
        import tempfile
        import threading

        from da4ml_tpu.cmvm import solve as _solve
        from da4ml_tpu.store import store_at
        from da4ml_tpu.telemetry.metrics import metrics_snapshot

        def _counter(name_: str) -> int:
            return int(metrics_snapshot().get(name_, {}).get('value', 0))

        rng = np.random.default_rng(11000)
        n = 8 if limited else 24
        kernels = [_rand_kernel(rng, int(rng.integers(4, 13)), int(rng.integers(4, 13)), 4) for _ in range(n)]
        with tempfile.TemporaryDirectory() as td:
            store = store_at(os.path.join(td, 'store'))
            cold, cold_ms = [], []
            for k in kernels:
                t0 = time.perf_counter()
                cold.append(_solve(k, backend=host_backend, store=store))
                cold_ms.append((time.perf_counter() - t0) * 1e3)
            hits0 = _counter('store.hits')
            warm, warm_ms = [], []
            for k in kernels:
                t0 = time.perf_counter()
                warm.append(_solve(k, backend=host_backend, store=store))
                warm_ms.append((time.perf_counter() - t0) * 1e3)
            hit_ratio = (_counter('store.hits') - hits0) / n
            bit_exact = all(
                json.dumps(a.to_dict(), sort_keys=True) == json.dumps(b.to_dict(), sort_keys=True)
                for a, b in zip(cold, warm)
            )
            # 6 threads race one fresh key: single-flight must collapse the
            # herd to one search (one publish), the rest answer from disk
            herd_kernel = _rand_kernel(rng, 10, 10, 4)
            n_threads = 6
            barrier = threading.Barrier(n_threads)

            def _race():
                barrier.wait()
                _solve(herd_kernel, backend=host_backend, store=store)

            pubs0 = _counter('store.publishes')
            threads = [threading.Thread(target=_race) for _ in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            herd_searches = _counter('store.publishes') - pubs0
        cold_p50 = float(np.percentile(cold_ms, 50))
        hit_p50 = float(np.percentile(warm_ms, 50))
        return {
            'n_kernels': n,
            'cold_p50_ms': round(cold_p50, 3),
            'hit_p50_ms': round(hit_p50, 3),
            'warm_speedup': round(cold_p50 / hit_p50, 2) if hit_p50 > 0 else None,
            'hit_ratio': round(hit_ratio, 4),
            'bit_exact': bit_exact,
            'herd_threads': n_threads,
            'herd_searches': herd_searches,
            'singleflight_dedup': n_threads - herd_searches,
        }
    if name == 'fleet':
        # replica-fleet probe (docs/serving.md#replica-fleets): the full
        # chaos drill at bench scale — 4 serve subprocesses behind the
        # hedging router, one SIGKILL + one hot reload under load, warm-
        # from-shared proven via the tier counters. The headline pair
        # (fleet.samples_per_s floor, fleet.p99_ms ceiling) is what
        # ci/budgets.toml gates.
        from da4ml_tpu.serve.chaos import fleet_chaos_drill

        report = fleet_chaos_drill(replicas=4, duration_s=6.0 if limited else 10.0)
        load = report['load']
        return {
            'ok': report['ok'],
            'replicas': 4,
            'requests': load['requests'],
            'samples_per_s': load['samples_per_s'],
            'p50_ms': load['p50_ms'],
            'p99_ms': load['p99_ms'],
            'availability': load['availability'],
            'bit_exact': load['mismatches'] == 0,
            'errors': load['errors'],
            'single_stream_samples_per_s': report['phases']['baseline']['single_stream_samples_per_s'],
            'speedup_vs_single_stream': report['speedup_vs_single_stream'],
            'checks_failed': sorted(k for k, v in report['checks'].items() if not v),
        }
    if name == 'select_modes':
        # selection-mode microbench: top4 (XLA O(S*P) score cache) vs the
        # full-rescan xla path vs the single-kernel fused Pallas loop
        from da4ml_tpu.cmvm.jax_search import _build_cse_fn

        k1 = _section_kernels('1_16x16_int4', n1, limited)
        out = {}
        for mode in ('top4', 'xla', 'fused'):
            os.environ['DA4ML_JAX_SELECT'] = mode
            _build_cse_fn.cache_clear()
            try:
                _, steady, compile_t = _jax_solve(k1)
            finally:
                os.environ.pop('DA4ML_JAX_SELECT', None)
                _build_cse_fn.cache_clear()
            out[f'{mode}_rate'] = round(len(k1) / steady, 3)
            out[f'{mode}_compile_s'] = round(compile_t, 2)
        out['top4_vs_xla'] = round(out['top4_rate'] / out['xla_rate'], 3)
        out['fused_vs_top4'] = round(out['fused_rate'] / out['top4_rate'], 3)
        return out
    return _with_shape_classes(_run_config(name, _section_kernels(name, n1, limited), host_backend))


_CONFIG_SECTIONS = (
    '1_16x16_int4',
    '2_jedi_mlp_layers',
    '3_dim_bits_sweep',
    '3b_large_dim',
    '4_qconv3x3_im2col',
    '5_full_model_trace',
)
_MICRO_SECTIONS = ('quality_sweep', 'quality_beam', 'select_modes', 'dais_inference', 'campaign', 'serve', 'store', 'fleet')


def _run_section_child(name: str, n1: int, timeout: float, env: dict | None = None) -> dict:
    """One bench section in a bounded child; the last JSON stdout line wins.

    Raises subprocess.TimeoutExpired through (callers decide wedge policy);
    any other failure comes back as an {'error': ...} entry.
    """
    r = subprocess.run(
        [sys.executable, sys.argv[0], '--section', name, str(n1)],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )
    lines = [ln for ln in (r.stdout or '').strip().splitlines() if ln.startswith('{')]
    if r.returncode == 0 and lines:
        return json.loads(lines[-1])
    tail = (r.stderr or '').strip().splitlines()[-3:]
    return {'error': (' | '.join(tail))[-300:] or f'rc={r.returncode}'}


def _resume_campaign_kernels():
    """The fixed 3-kernel campaign of the --resume-check drill."""
    rng = np.random.default_rng(20260804)
    return [_rand_kernel(rng, 12, 12, 4) for _ in range(3)]


def _resume_child(ckpt: str) -> None:
    """Child mode: run the drill campaign against `ckpt` (killed by the
    parent's injected fault after the first durable save on pass 1)."""
    from da4ml_tpu.reliability import solve_many

    results, report = solve_many(_resume_campaign_kernels(), backend='auto', checkpoint=ckpt)
    print(json.dumps({'n_done': len(results), 'checkpoint_hits': report.checkpoint_hits}))


def run_resume_check() -> dict:
    """Self-check of crash-safe checkpointed resume (docs/reliability.md):
    a 3-kernel campaign is started in a child that is hard-killed
    (``os._exit`` via fault injection) right after its first result is
    durable, then resumed in a second child; the resumed results must be
    byte-identical to an uninterrupted in-process run.
    """
    import tempfile

    from da4ml_tpu.ir import Pipeline
    from da4ml_tpu.reliability import CheckpointStore, solve_many

    out: dict = {}
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, 'campaign.json')
        env = dict(os.environ, DA4ML_FAULT_INJECT='checkpoint.post_save=kill:1')
        r1 = subprocess.run(
            [sys.executable, sys.argv[0], '--resume-child', ckpt], capture_output=True, text=True, timeout=300, env=env
        )
        out['killed_rc'] = r1.returncode
        out['records_after_kill'] = len(CheckpointStore(ckpt).records)
        env2 = dict(os.environ)
        env2.pop('DA4ML_FAULT_INJECT', None)
        r2 = subprocess.run(
            [sys.executable, sys.argv[0], '--resume-child', ckpt], capture_output=True, text=True, timeout=300, env=env2
        )
        out['resume_rc'] = r2.returncode
        lines = [ln for ln in (r2.stdout or '').splitlines() if ln.startswith('{')]
        out['resume'] = json.loads(lines[-1]) if lines else None
        resumed = [Pipeline.from_dict(rec['pipeline']) for rec in CheckpointStore(ckpt).records.values()]

    fresh, _ = solve_many(_resume_campaign_kernels(), backend='auto')
    fresh_dicts = sorted(json.dumps(p.to_dict(), sort_keys=True) for p in fresh)
    resumed_dicts = sorted(json.dumps(p.to_dict(), sort_keys=True) for p in resumed)
    out['identical_to_uninterrupted'] = fresh_dicts == resumed_dicts
    out['ok'] = (
        out['killed_rc'] != 0
        and out['records_after_kill'] == 1
        and out['resume_rc'] == 0
        and bool(out['resume'])
        and out['resume']['checkpoint_hits'] == 1
        and out['identical_to_uninterrupted']
    )
    return out


def main():
    n1 = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    detail: dict = {'host_threads': HOST_THREADS, 'nproc': os.cpu_count()}

    forced_cpu = os.environ.get('DA4ML_BENCH_PLATFORM') == 'cpu'
    platform, probe_err = probe_tpu()
    is_tpu = platform not in (None, 'cpu')  # a 'cpu' platform is a valid host, not a TPU
    # Any CPU XLA run — probe failure, forced, or a host with no TPU at all
    # (probe succeeds with platform 'cpu') — uses the shrunken workloads:
    # the full-size device sections are sized for a TPU and blow the
    # wall-clock budget on a host CPU (round-6 finding: a no-TPU host with
    # a HEALTHY probe previously ran the full sweep and timed out).
    limited = not is_tpu
    if platform is None:
        # a deliberate cpu run is not a TPU failure — report it separately
        detail['platform_forced' if forced_cpu else 'tpu_error'] = probe_err
    if limited:
        os.environ['DA4ML_BENCH_PLATFORM'] = 'cpu'
        os.environ['JAX_PLATFORMS'] = 'cpu'
    detail['platform'] = platform or ('cpu-forced' if forced_cpu else 'cpu-fallback')
    if platform is None and not forced_cpu:
        # a real-TPU outage at capture time: attach the committed snapshot of
        # the last successful on-TPU measurement, clearly labeled as a PRIOR
        # measurement (docs/bench_snapshot.json) — never as the live result
        try:
            snap_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), 'docs', 'bench_snapshot.json')
            with open(snap_path) as fh:
                detail['last_known_tpu'] = json.load(fh)
        except Exception as e:  # make a missing/invalid snapshot visible, not silent
            detail['last_known_tpu_error'] = f'{type(e).__name__}: {e}'[:200]
    detail['host_backend'] = _resolve_host_backend()
    detail['limited_cpu_fallback'] = limited

    # wall-clock budget: degrade to fewer sections rather than timing out
    # without printing the JSON line
    budget_s = float(os.environ.get('DA4ML_BENCH_BUDGET_S', '600'))
    deadline = time.monotonic() + budget_s

    # Every section runs in its own bounded subprocess: a device hang or a
    # worker crash loses that section, not the bench. The persistent XLA
    # compile cache is shared, so the per-child init cost stays modest.
    detail['configs'] = []
    wedged = False
    sections = _CONFIG_SECTIONS + _MICRO_SECTIONS
    for name in sections:
        if name == 'select_modes' and not is_tpu:
            continue  # interpret-mode numbers are meaningless
        remaining = deadline - time.monotonic()
        if remaining < 30 or wedged:
            detail.setdefault('skipped_configs', []).append(name)
            continue
        tmo = min(max(remaining + 30.0, 60.0), 560.0)
        try:
            entry = _run_section_child(name, n1, tmo)
        except subprocess.TimeoutExpired:
            entry = {'error': f'section timed out after {tmo:.0f}s'}
            # a hung device call on the real TPU means the tunnel is gone;
            # on a CPU host a timeout is just a slow section — keep going
            wedged = is_tpu
            if wedged:
                detail['tpu_wedged_after'] = name
        if name in _CONFIG_SECTIONS:
            entry.setdefault('config', name)
            detail['configs'].append(entry)
        else:
            detail[name] = entry

    c1 = detail['configs'][0] if detail['configs'] else {}

    # cold/warm split of the full-model conversion, surfaced at top level
    # (VERDICT r4 item 3: cold <= 2x warm is the target)
    for e in detail['configs']:
        if e.get('config') == '5_full_model_trace' and e.get('jax_s') and e.get('jax_cold_s'):
            detail['full_model_cold_over_warm'] = round(e['jax_cold_s'] / e['jax_s'], 2)

    # adaptive headline: when the live select_modes A/B shows the fused
    # kernel beating the default top4 loop, re-measure config 1 under fused
    # and report that as the headline. The mode is recorded in the entry —
    # reproduce with DA4ML_JAX_SELECT=fused.
    sm = detail.get('select_modes') or {}
    re_budget = deadline - time.monotonic()
    if is_tpu and not wedged and sm.get('fused_rate', 0) > sm.get('top4_rate', 0) and re_budget > 45:
        try:
            cf = _run_section_child(
                '1_16x16_int4', n1, min(re_budget + 30.0, 560.0), env=dict(os.environ, DA4ML_JAX_SELECT='fused')
            )
            if cf.get('jax_rate', 0) > c1.get('jax_rate', 0):
                cf['config'] = '1_16x16_int4'
                cf['headline_select'] = 'fused'
                detail['config1_top4'] = c1
                detail['configs'][0] = cf
                c1 = cf
        except Exception as e:
            detail['headline_fused_error'] = f'{type(e).__name__}: {e}'[:200]

    doc = {
        'metric': 'cmvm_solve_throughput_16x16_int4',
        'value': c1.get('jax_rate', 0.0),
        'unit': 'matrices/s/chip',
        'vs_baseline': c1.get('speedup', 0.0),
        'detail': detail,
    }
    print(json.dumps(doc))
    # --out: the same document as a file, the input `da4ml-tpu bench-diff`
    # gates against a committed baseline (docs/observability.md#budgets)
    if _OUT_PATH:
        with open(_OUT_PATH, 'w') as fh:
            json.dump(doc, fh)


def _parse_cache_flags(argv: list[str]) -> list[str]:
    """Strip --cache-dir/--no-persistent-cache, arming the env they map to.

    Applied before any section spawns so child processes inherit the same
    cache policy: cold-vs-warm cache runs are both measurable
    (``--no-persistent-cache`` for a guaranteed-cold in-process compile,
    ``--cache-dir`` pointing at a shared path for cross-process warm runs).
    """
    global _OUT_PATH
    out = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == '--no-persistent-cache':
            os.environ['DA4ML_XLA_CACHE'] = '0'
        elif a == '--no-device-resident':
            # A/B flag: legacy host-state rung loop (per-rung fetch/re-upload)
            # so a capture pair shows the device-resident ladder's delta on
            # identical hardware (docs/benchmarks.md#device-resident)
            os.environ['DA4ML_JAX_DEVICE_RESIDENT'] = '0'
        elif a == '--cache-dir' and i + 1 < len(argv):
            os.environ['DA4ML_XLA_CACHE'] = argv[i + 1]
            i += 1
        elif a.startswith('--cache-dir='):
            os.environ['DA4ML_XLA_CACHE'] = a.split('=', 1)[1]
        elif a == '--out' and i + 1 < len(argv):
            _OUT_PATH = argv[i + 1]
            i += 1
        elif a.startswith('--out='):
            _OUT_PATH = a.split('=', 1)[1]
        else:
            out.append(a)
        i += 1
    return out


#: set by --out: also write the bench JSON document to this path
_OUT_PATH: str | None = None


if __name__ == '__main__':
    sys.argv[1:] = _parse_cache_flags(sys.argv[1:])
    if len(sys.argv) >= 3 and sys.argv[1] == '--resume-child':
        _resume_child(sys.argv[2])
        raise SystemExit(0)
    if len(sys.argv) >= 2 and sys.argv[1] in ('--resume', '--resume-check'):
        _check = run_resume_check()
        print(json.dumps({'metric': 'resume_check', 'value': int(_check.get('ok', False)), 'detail': _check}))
        raise SystemExit(0 if _check.get('ok') else 1)
    if len(sys.argv) >= 3 and sys.argv[1] == '--section':
        # child mode: run one section, print its result as one JSON line
        _name = sys.argv[2]
        _n1 = int(sys.argv[3]) if len(sys.argv) > 3 else 64
        _limited = os.environ.get('DA4ML_BENCH_PLATFORM') == 'cpu'
        print(json.dumps(run_section(_name, _n1, _limited)))
        raise SystemExit(0)
    try:
        main()
    except Exception as e:  # never die without the JSON line
        print(
            json.dumps(
                {
                    'metric': 'cmvm_solve_throughput_16x16_int4',
                    'value': 0.0,
                    'unit': 'matrices/s/chip',
                    'vs_baseline': 0.0,
                    'detail': {'error': f'{type(e).__name__}: {e}'[:500]},
                }
            )
        )
        raise SystemExit(0)
