"""Benchmark: CMVM DA-search throughput, JAX/TPU backend vs 16-thread host baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Headline (BASELINE.md config 1): batch-solve random 16x16 int4 kernels on the
JAX backend vs the native C++/OpenMP solver pinned to 16 threads (the
BASELINE.json baseline). detail[] adds config 2 (JEDI-linear MLP layer
kernels), config 3 (dim x bits random sweep), config 4 (QConv2D 3x3 kernels
as im2col constant blocks [kh*kw*Cin, Cout]), and config 5 (a full MLP+Conv
model traced end to end, jax vs cpp solver backend), plus the
compile-vs-search time split of the JAX path.

Robustness: the axon TPU plugin can *hang* (not just fail) at backend init,
so the TPU is probed in a bounded subprocess with retries; on failure the
bench runs the device path on CPU XLA and records the probe error in the
JSON line instead of crashing (round-1 failure mode: BENCH_r01 rc=1).

Acceptance per matrix (BASELINE.md): Pipeline.kernel == kernel exactly and
total cost <= host's.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

HOST_THREADS = 16  # BASELINE.json: 16-thread OpenMP baseline

_PROBE_SRC = "import jax; d = jax.devices(); print('PLATFORM=' + d[0].platform)"


def probe_tpu(attempts: int = 2, timeout: float = 90.0):
    """Bounded-subprocess TPU probe with backoff. Returns (platform|None, err).

    The probe inherits the parent environment unchanged, so the platform it
    reports is the one the timed run below will actually initialize.
    """
    err = None
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, '-c', _PROBE_SRC],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
            lines = r.stdout.strip().splitlines()
            if r.returncode == 0 and lines and lines[-1].startswith('PLATFORM='):
                return lines[-1].split('=', 1)[1], None
            tail = (r.stderr or '').strip().splitlines()
            err = (tail[-1] if tail else f'probe rc={r.returncode}')[:300]
        except subprocess.TimeoutExpired:
            err = f'TPU init probe timed out after {timeout:.0f}s'
        if i + 1 < attempts:
            time.sleep(10.0 * (i + 1))
    return None, err


def _rand_kernel(rng, n_in, n_out, bits):
    mag = rng.integers(0, 2**bits, (n_in, n_out)).astype(np.float64)
    return mag * rng.choice([-1.0, 1.0], (n_in, n_out))


def _host_solve(kernels, backend):
    from da4ml_tpu.cmvm import solve

    t0 = time.perf_counter()
    sols = [solve(k, backend=backend, n_workers=HOST_THREADS) for k in kernels]
    return sols, time.perf_counter() - t0


def _jax_solve(kernels):
    """(solutions, steady_time, compile_time): first call pays XLA compiles."""
    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    t0 = time.perf_counter()
    solve_jax_many(kernels)
    first = time.perf_counter() - t0
    t0 = time.perf_counter()
    sols = solve_jax_many(kernels)
    steady = time.perf_counter() - t0
    return sols, steady, max(first - steady, 0.0)


def _parity(kernels, jax_sols, host_sols):
    n_exact = sum(int(np.array_equal(np.asarray(s.kernel, np.float64), k)) for k, s in zip(kernels, jax_sols))
    return {
        'exact': f'{n_exact}/{len(kernels)}',
        'mean_cost_jax': round(float(np.mean([s.cost for s in jax_sols])), 3),
        'mean_cost_host': round(float(np.mean([s.cost for s in host_sols])), 3),
    }


def _run_config(name, kernels, host_backend):
    host_sols, host_t = _host_solve(kernels, host_backend)
    jax_sols, jax_t, compile_t = _jax_solve(kernels)
    n = len(kernels)
    entry = {
        'config': name,
        'n_matrices': n,
        'host_rate': round(n / host_t, 3),
        'jax_rate': round(n / jax_t, 3),
        'speedup': round(host_t / jax_t, 3),
        'jax_compile_s': round(compile_t, 2),
        **_parity(kernels, jax_sols, host_sols),
    }
    return entry


def _trace_model(backend: str, limited: bool):
    """Trace the config-5 model (BASELINE.md: MLP+Conv, all layers CMVM)."""
    import da4ml_tpu.trace.ops.conv_utils as cu
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    rng = np.random.default_rng(5)
    side, cin, cmid, dense = (4, 2, 4, 8) if limited else (8, 3, 8, 32)
    inp = FixedVariableArrayInput((side, side, cin), hwconf=HWConfig(1, -1, -1), solver_options={'backend': backend})
    x = inp.quantize(np.ones((side, side, cin)), np.full((side, side, cin), 3), np.full((side, side, cin), 2))
    w1 = rng.integers(-32, 32, (3, 3, cin, cmid)).astype(np.float64)
    x = cu.conv2d(x, w1, padding='same')
    x = x.relu(i=np.full(x.shape, 6), f=np.full(x.shape, 2))
    x = cu.max_pool2d(x, 2)
    x = x.reshape(-1)
    w2 = rng.integers(-32, 32, (x.shape[0], dense)).astype(np.float64)
    x = (x @ w2).relu(i=np.full(dense, 7), f=np.full(dense, 2))
    w3 = rng.integers(-32, 32, (dense, 5)).astype(np.float64)
    return comb_trace(inp, x @ w3)


def _run_model_config(limited: bool, host_backend: str = 'cpp'):
    """Config 5: end-to-end model build time (trace + every CMVM solve)."""
    t0 = time.perf_counter()
    comb_host = _trace_model(host_backend, limited)
    host_t = time.perf_counter() - t0
    t0 = time.perf_counter()
    comb_jax = _trace_model('jax', limited)
    jax_t = time.perf_counter() - t0
    return {
        'config': '5_full_model_trace',
        'host_s': round(host_t, 3),
        'jax_s': round(jax_t, 3),
        'speedup': round(host_t / jax_t, 3),
        'cost_jax': float(comb_jax.cost),
        'cost_host': float(comb_host.cost),
    }


def _run_inference_micro(limited: bool):
    """DAIS inference samples/s: jitted device kernel vs native interpreter."""
    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.runtime.jax_backend import DaisExecutor
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    rng = np.random.default_rng(11)
    n_in, hidden = (8, 16) if limited else (16, 64)
    inp = FixedVariableArrayInput(n_in, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(n_in), np.full(n_in, 3), np.full(n_in, 2))
    w1 = rng.integers(-8, 8, (n_in, hidden)).astype(np.float64)
    x = (x @ w1).relu(i=np.full(hidden, 6), f=np.full(hidden, 2))
    w2 = rng.integers(-8, 8, (hidden, 8)).astype(np.float64)
    comb = comb_trace(inp, x @ w2)

    n_samples = 4096 if limited else 262144
    data = rng.uniform(-8, 8, (n_samples, n_in))

    ex = DaisExecutor(decode(comb.to_binary()))
    out_dev = ex(data)  # first call pays the compile
    t0 = time.perf_counter()
    out_dev = ex(data)
    dev_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    out_host = comb.predict(data, n_threads=HOST_THREADS)
    host_t = time.perf_counter() - t0
    return {
        'n_samples': n_samples,
        'device_rate': round(n_samples / dev_t, 1),
        'host_rate': round(n_samples / host_t, 1),
        'speedup': round(host_t / dev_t, 3),
        'bit_exact': bool(np.array_equal(out_dev, out_host)),
    }


def main():
    n1 = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    detail: dict = {'host_threads': HOST_THREADS, 'nproc': os.cpu_count()}

    platform, probe_err = probe_tpu()
    if platform is None:
        # run the device path on CPU XLA so a number still gets recorded
        os.environ['JAX_PLATFORMS'] = 'cpu'
        detail['tpu_error'] = probe_err
    import jax

    if platform is None:
        jax.config.update('jax_platforms', 'cpu')
    detail['platform'] = platform or 'cpu-fallback'
    # persistent compilation cache: staged-search shape classes compile once
    # per machine, not once per bench run
    try:
        jax.config.update('jax_compilation_cache_dir', os.environ.get('DA4ML_JAX_CACHE', '/tmp/da4ml_jax_cache'))
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
    except Exception:
        pass

    try:
        from da4ml_tpu.native import has_solver

        host_backend = 'cpp' if has_solver() else 'cpu'
    except Exception:
        host_backend = 'cpu'
    detail['host_backend'] = host_backend

    rng = np.random.default_rng(20260729)

    # wall-clock budget: CPU-XLA fallback searches are slow; degrade to fewer
    # configs rather than timing out without printing the JSON line
    budget_s = float(os.environ.get('DA4ML_BENCH_BUDGET_S', '420'))
    deadline = time.monotonic() + budget_s
    # on CPU fallback also shrink the workloads — the recorded number is
    # informational there, the real measurement happens on the TPU
    limited = platform is None
    detail['limited_cpu_fallback'] = limited

    # config 1 (headline): 16x16 int4 batch
    k1 = [_rand_kernel(rng, 16, 16, 4) for _ in range(min(n1, 16) if limited else n1)]
    c1 = _run_config('1_16x16_int4', k1, host_backend)
    detail['configs'] = [c1]
    # config 2: JEDI-linear MLP layer kernels, 6-bit
    shapes2 = ((16, 64), (64, 32), (32, 32), (32, 5))
    if limited:
        shapes2 = tuple((ni, no) for ni, no in shapes2 if max(ni, no) <= 32)
    k2 = [_rand_kernel(rng, ni, no, 6) for ni, no in shapes2]
    # config 3: random dim x bits sweep, batched
    shapes3 = ((8, 2), (8, 8), (16, 4), (32, 4), (32, 8), (64, 2), (64, 6))
    if limited:
        shapes3 = tuple((d, b) for d, b in shapes3 if d <= 16)
    k3 = [_rand_kernel(rng, d, d, b) for d, b in shapes3]
    # config 4: QConv2D 3x3 kernels unrolled to im2col blocks [9*Cin, Cout]
    shapes4 = ((1, 8), (4, 8), (8, 16), (16, 16))
    if limited:
        shapes4 = tuple((ci, co) for ci, co in shapes4 if 9 * ci <= 36)
    k4 = [_rand_kernel(rng, 9 * ci, co, 6) for ci, co in shapes4]
    for name, ks in (('2_jedi_mlp_layers', k2), ('3_dim_bits_sweep', k3), ('4_qconv3x3_im2col', k4)):
        if time.monotonic() > deadline:
            detail.setdefault('skipped_configs', []).append(name)
            continue
        detail['configs'].append(_run_config(name, ks, host_backend))

    # config 5: full MLP+Conv model traced end to end (trace + all solves)
    if time.monotonic() < deadline:
        try:
            detail['configs'].append(_run_model_config(limited, host_backend))
        except Exception as e:
            detail['model_config_error'] = f'{type(e).__name__}: {e}'[:200]
    else:
        detail.setdefault('skipped_configs', []).append('5_full_model_trace')

    # solution-quality axis: widening the device sweep with a second
    # selection heuristic costs only extra lanes — report the cost win
    if time.monotonic() < deadline:
        try:
            from da4ml_tpu.cmvm.jax_search import solve_jax_many

            t0 = time.perf_counter()
            wide = solve_jax_many(k1, method0_candidates=['wmc', 'mc'])
            detail['quality_sweep'] = {
                'mean_cost_wide': round(float(np.mean([s.cost for s in wide])), 3),
                'mean_cost_single': c1['mean_cost_jax'],
                'wall_s': round(time.perf_counter() - t0, 2),
            }
        except Exception as e:
            detail['quality_sweep'] = {'error': f'{type(e).__name__}: {e}'[:200]}

    # DAIS batch-inference throughput: jitted XLA integer kernel vs the
    # native OpenMP interpreter (the reference's sample-parallel axis,
    # src/da4ml/_binary/dais/bindings.cc:58-96 of calad0i/da4ml)
    if time.monotonic() < deadline:
        try:
            detail['dais_inference'] = _run_inference_micro(limited)
        except Exception as e:
            detail['dais_inference'] = {'error': f'{type(e).__name__}: {e}'[:200]}

    # fused Pallas selection vs XLA select microbench (real TPU only)
    if platform is not None and platform != 'cpu' and time.monotonic() < deadline:
        try:
            from da4ml_tpu.cmvm.jax_search import _build_cse_fn

            os.environ['DA4ML_JAX_SELECT'] = 'pallas'
            _build_cse_fn.cache_clear()
            try:
                _, p_steady, p_compile = _jax_solve(k1)
            finally:
                os.environ.pop('DA4ML_JAX_SELECT', None)
                _build_cse_fn.cache_clear()
            p_rate = round(len(k1) / p_steady, 3)
            detail['pallas_select'] = {
                'jax_rate': p_rate,
                'vs_xla_select': round(p_rate / c1['jax_rate'], 3) if c1['jax_rate'] else None,
                'jax_compile_s': round(p_compile, 2),
            }
        except Exception as e:
            detail['pallas_select'] = {'error': f'{type(e).__name__}: {e}'[:200]}

    print(
        json.dumps(
            {
                'metric': 'cmvm_solve_throughput_16x16_int4',
                'value': c1['jax_rate'],
                'unit': 'matrices/s/chip',
                'vs_baseline': c1['speedup'],
                'detail': detail,
            }
        )
    )


if __name__ == '__main__':
    try:
        main()
    except Exception as e:  # never die without the JSON line
        print(
            json.dumps(
                {
                    'metric': 'cmvm_solve_throughput_16x16_int4',
                    'value': 0.0,
                    'unit': 'matrices/s/chip',
                    'vs_baseline': 0.0,
                    'detail': {'error': f'{type(e).__name__}: {e}'[:500]},
                }
            )
        )
        raise SystemExit(0)
